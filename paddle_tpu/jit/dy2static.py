"""Dy2Static: AST conversion of Python control flow for `to_static`.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/ — the AST
transformer (`jit/dy2static`) plus the runtime converters
`convert_operators.py:108` (convert_while_loop) and `:329`
(convert_ifelse): dygraph code whose `if`/`while`/`for` depends on a
TENSOR value is rewritten so it can compile into the static graph, while
Python-valued conditions keep ordinary eager semantics, decided at run
time.

TPU-native redesign (what changes vs the reference):

- A tensor-valued `if` does NOT lower to a two-branch cond op. Under a
  jax trace BOTH branches execute in the AMBIENT trace and every
  modified variable is merged with `jnp.where(pred, ...)` — the
  select-based form. This is deliberate: (a) the eager autograd tape
  records each branch's ops in the surrounding trace, so gradients flow
  through converted models with zero extra machinery (a lax.cond branch
  would capture tape nodes in a sub-trace the tape cannot replay); and
  (b) on TPU, XLA itself turns small conds into selects — branches both
  execute and the select picks lanes, which is the idiomatic compilation
  of data-dependent branching on a SIMD machine. The cost (both branches
  run; side effects of both happen at trace time) matches XLA semantics.
- A tensor-valued `while` (or `for i in range(tensor)`) lowers to
  `lax.while_loop` over the loop-modified variables. JAX cannot
  reverse-differentiate a while loop, so converted tensor-while loops
  are for non-differentiated code paths (decoding, clipping loops …) —
  the same places the reference uses them.
- `a and b` / `or` / `not` convert to runtime-dispatched helpers:
  short-circuit Python semantics for Python values, `logical_*` for
  traced tensors (both operands evaluate — XLA has no short circuit).
- Every call site is wrapped in `convert_call`, which recursively
  converts user functions and `Layer.forward` bodies on first use (the
  reference's `convert_call`), so control flow inside a model's forward
  converts even when only the train step carries `@to_static`.

- `break` / `continue` under tensor conditions convert via the
  reference's guard-flag form (break_continue_transformer.py:1): a
  `break` becomes a loop-carried bool flag set under the (converted)
  condition, statements after the set point are guard-wrapped in
  `if not flag:`, and the loop test gains `not flag and ...`; a
  for-range with break lowers to the explicit while form so the test
  can carry the flag. `continue` uses a per-iteration flag reset at the
  top of the body. Under python-valued conditions the flags stay python
  bools and the loop exits eagerly at the next test, preserving eager
  semantics.
- Early `return` (return_transformer.py:1 / early_return_transformer):
  instead of the reference's return-flag, guard-clause returns are
  NORMALIZED — the statements after `if c: return v` are pushed into
  its `else`, recursively, producing the both-branches-return form that
  `convert_ifelse_ret` merges with one select. `return` inside a
  tensor-converted LOOP stays unsupported (a lax.while_loop carry
  cannot hold a value first bound mid-loop); such loops are left as
  plain Python and a tensor condition there raises loudly.

Conversion is best-effort and safe: any function whose source is
unavailable, or any construct outside the supported subset (e.g.
`return` inside a converted loop, `break` in a non-range `for`), is
left as plain Python — correct eagerly, and a tensor-valued condition
there still raises the usual concretization error pointing here.

A variable bound in only ONE branch of a converted `if` merges to a
poison sentinel whose every ordinary read (arithmetic, comparison by
value, bool, str/format, hash, call, index) raises NameError — and the
one read Python does not let the sentinel intercept, the `is` operator,
is rejected at CONVERSION time instead: an identity test against a
maybe-unbound name raises `TraceHazardError` (TL005) naming the
variable, so `maybe_bound is None` can never silently evaluate False
under a trace.  Bind the variable on every path when its identity is
tested.
"""
from __future__ import annotations

import ast
import inspect
import linecache
import textwrap
import threading
import types
import weakref

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = [
    "convert_call",
    "convert_ifelse",
    "convert_ifelse_ret",
    "convert_while",
    "convert_logical_and",
    "convert_logical_or",
    "convert_logical_not",
    "convert_to_static",
    "guard_unconvertible",
    "UNDEF",
]


class _Undefined:
    """Sentinel for a variable not yet bound before a converted branch.

    Any USE raises NameError, mirroring Python's unbound-local semantics
    as closely as a sentinel can: code like `if c: y = f(x)` followed by
    `try: use(y) except NameError: ...` keeps working after conversion
    because touching the sentinel raises the same exception class the
    untransformed code would.
    """

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<dy2static-undefined>"

    def _raise(self, *a, **k):
        raise NameError(
            "dy2static: variable used before assignment (bound in only "
            "one branch of a converted `if`, or a loop temporary read "
            "after a tensor-converted `while`)")

    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        self._raise()

    __bool__ = __call__ = __len__ = __iter__ = __getitem__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __truediv__ = __rtruediv__ = __matmul__ = __rmatmul__ = _raise
    __lt__ = __le__ = __gt__ = __ge__ = _raise
    __neg__ = __abs__ = __float__ = __int__ = __index__ = _raise
    # equality / formatting / hashing are reads too: `status == 'done'`
    # or f"{status}" on a poisoned variable must raise, not silently
    # take the wrong path (repr stays printable for debugging)
    __eq__ = __ne__ = __str__ = __format__ = __hash__ = _raise


UNDEF = _Undefined()

_SKIP_MODULE_ROOTS = (
    "paddle_tpu", "jax", "jaxlib", "numpy", "np", "torch", "builtins",
    "functools", "itertools", "typing", "collections", "math", "operator",
)

_GEN_PREFIX = "__ptd2s_"

# builtins whose call-site semantics depend on being called by name
_NO_WRAP_NAMES = {
    "super", "range", "len", "print", "isinstance", "issubclass", "type",
    "getattr", "setattr", "hasattr", "enumerate", "zip", "map", "filter",
    "locals", "globals", "vars", "eval", "exec", "iter", "next", "id",
    "repr", "str", "int", "float", "bool", "list", "tuple", "dict", "set",
    "min", "max", "abs", "sum", "sorted", "reversed", "format",
}


# ----------------------------------------------------------------- runtime
def _is_traced(v):
    if isinstance(v, Tensor):
        v = v._value
    return isinstance(v, jax.core.Tracer)


def _truth(v):
    if isinstance(v, Tensor):
        return bool(v.numpy())
    return bool(v)


def _unwrap(v):
    return v._value if isinstance(v, Tensor) else v


def _select_var(pred, t, f):
    """Merge one variable's two branch values under a traced predicate."""
    if t is f:
        return t
    if t is UNDEF or f is UNDEF:
        # bound on only one side: a maybe-bound value is not
        # representable under a trace, so the merge POISONS the name —
        # dead temporaries (loop targets etc.) pass through silently,
        # and any actual READ raises the sentinel's NameError
        return UNDEF
    if isinstance(t, Tensor) or isinstance(f, Tensor):
        # through dispatch.apply so the select is a TAPE op — gradients
        # flow into both branches' subgraphs (d/dt where(p,t,f) masks the
        # untaken side to zero)
        from paddle_tpu.core.dispatch import apply
        return apply(lambda pv, tv, fv: jnp.where(
            jnp.reshape(pv, ()), tv, fv), pred, t, f)
    if isinstance(t, jax.Array) or isinstance(f, jax.Array) or \
            _is_traced(t) or _is_traced(f):
        return jnp.where(_unwrap(pred).reshape(()), _unwrap(t), _unwrap(f))
    if isinstance(t, (list, tuple)) and type(t) is type(f) and \
            len(t) == len(f):
        return type(t)(_select_var(pred, a, b) for a, b in zip(t, f))
    if isinstance(t, dict) and isinstance(f, dict) and \
            set(t.keys()) == set(f.keys()):
        return {k: _select_var(pred, t[k], f[k]) for k in t}
    if isinstance(t, (int, float, bool, complex)) and \
            isinstance(f, (int, float, bool, complex)):
        return Tensor(jnp.where(_unwrap(pred).reshape(()), t, f))
    if t == f:
        return t
    raise TypeError(
        f"dy2static: cannot merge branch values of types "
        f"{type(t).__name__} / {type(f).__name__} under a tensor-valued "
        f"`if` — only tensors, numbers and matching containers merge")


def convert_ifelse(pred, true_fn, false_fn, get_args, set_args):
    """Assignment-style converted `if` (branches mutate via nonlocal)."""
    if _is_traced(pred):
        init = get_args()
        true_fn()
        tvals = get_args()
        set_args(init)
        false_fn()
        fvals = get_args()
        set_args(init)
        return tuple(_select_var(pred, t, f) for t, f in zip(tvals, fvals))
    if _truth(pred):
        true_fn()
    else:
        false_fn()
    return get_args()


def convert_ifelse_ret(pred, true_fn, false_fn):
    """Converted `if` whose two branches both end in `return`."""
    if _is_traced(pred):
        tv = true_fn()
        fv = false_fn()
        if tv is None and fv is None:
            return None
        return _select_var(pred, tv, fv)
    return true_fn() if _truth(pred) else false_fn()


def convert_while(cond_fn, body_fn, get_args, set_args, maybe_temp=None):
    """Converted `while`: lax.while_loop when the condition traces.

    ``maybe_temp[i]`` marks loop variables whose first body access is a
    STORE (per-iteration temporaries like Newton's ``nx``): when such a
    variable is unbound at loop entry it is excluded from the
    lax.while_loop carry instead of erroring — its post-loop value is
    the UNDEF sentinel (Python keeps the last iteration's value; reading
    it after a TENSOR-converted loop raises, loudly).
    """
    # the condition can BECOME traced mid-loop (a desugared break flag
    # flips to a where-merged tensor on the first tensor-valued
    # iteration) — re-dispatch every iteration; prior eager iterations
    # are simply trace-time-unrolled prefix steps
    while True:
        c = cond_fn()
        if _is_traced(c):
            break
        if not _truth(c):
            return get_args()
        body_fn()

    init = get_args()
    n = len(init)
    maybe_temp = maybe_temp or (False,) * n
    carry_idx = [i for i in range(n)
                 if not (init[i] is UNDEF and maybe_temp[i])]
    for i in carry_idx:
        if init[i] is UNDEF:
            raise ValueError(
                "dy2static: every variable read inside a tensor-valued "
                "`while` before being assigned must be bound before the "
                "loop (lax.while_loop carries need initial values)")
    was_tensor = [isinstance(init[i], Tensor) for i in carry_idx]

    def full(vals):
        out = [UNDEF] * n
        for j, i in enumerate(carry_idx):
            out[i] = Tensor(vals[j]) if was_tensor[j] else vals[j]
        return tuple(out)

    def c(vals):
        set_args(full(vals))
        out = cond_fn()
        return _unwrap(out).reshape(())

    def b(vals):
        set_args(full(vals))
        body_fn()
        cur = get_args()
        return tuple(_unwrap(cur[i]) for i in carry_idx)

    final = jax.lax.while_loop(
        c, b, tuple(_unwrap(init[i]) for i in carry_idx))
    set_args(full(final))
    return get_args()


def guard_unconvertible(value, code, filename, lineno):
    """Runtime guard planted on loops LEFT PLAIN by the transformer
    (return inside the body, `break` in a non-range `for`, loop `else:`).

    Eagerly it is a transparent pass-through.  Under a trace it raises
    the NAMED tracelint diagnostic (rule code + source line, wording
    shared with `tools/tracelint.py` via `analysis/rules.py`) instead of
    letting the loop condition die in an opaque jax concretization
    error deep inside the tracer.
    """
    if _is_traced(value):
        from paddle_tpu.analysis.rules import TraceHazardError
        raise TraceHazardError(code, filename, lineno)
    return value


def _as_bool(v):
    """bool-coerce a possibly-python operand for a traced logical op."""
    return jnp.asarray(_unwrap(v)).astype(bool)


def convert_logical_and(*fns):
    v = fns[0]()
    for f in fns[1:]:
        if _is_traced(v):
            w = f()  # no short circuit under a trace: both evaluate
            v = Tensor(jnp.logical_and(_as_bool(v), _as_bool(w)))
        else:
            if not _truth(v):
                return v
            v = f()
    return v


def convert_logical_or(*fns):
    v = fns[0]()
    for f in fns[1:]:
        if _is_traced(v):
            w = f()
            v = Tensor(jnp.logical_or(_as_bool(v), _as_bool(w)))
        else:
            if _truth(v):
                return v
            v = f()
    return v


def convert_logical_not(v):
    if _is_traced(v):
        return Tensor(jnp.logical_not(_unwrap(v).astype(bool)))
    return not _truth(v)


def make_range(*args):
    """range(...) operands for a converted for-loop: (start, stop, step)."""
    if len(args) == 1:
        return 0, args[0], 1
    if len(args) == 2:
        return args[0], args[1], 1
    return args[0], args[1], args[2]


def range_cond(i, stop, step):
    if _is_traced(i) or _is_traced(stop) or _is_traced(step):
        iv, sv, st = _unwrap(i), _unwrap(stop), _unwrap(step)
        up = jnp.logical_and(jnp.asarray(st) > 0, jnp.asarray(iv) < sv)
        dn = jnp.logical_and(jnp.asarray(st) < 0, jnp.asarray(iv) > sv)
        return Tensor(jnp.logical_or(up, dn))
    if (step if not isinstance(step, Tensor) else step.numpy()) > 0:
        return _lt(i, stop)
    return _lt(stop, i)


def _lt(a, b):
    av = a.numpy() if isinstance(a, Tensor) else a
    bv = b.numpy() if isinstance(b, Tensor) else b
    return bool(av < bv)


# -------------------------------------------------------------- transform
_fail_cache = weakref.WeakSet()
_layer_classes_done = weakref.WeakSet()
_local = threading.local()


def convert_call(f):
    """Recursively convert a callee on first use (reference convert_call)."""
    if f is None or isinstance(f, type):
        return f
    if getattr(f, "_not_to_static", False) or \
            getattr(f, "_ptd2s_transformed", False):
        return f
    try:
        from paddle_tpu.nn.layer.layers import Layer
        if isinstance(f, Layer):
            _transform_layer_forward(f)
            return f
    except Exception:
        return f
    from paddle_tpu.jit.api import StaticFunction
    if isinstance(f, StaticFunction):
        return f
    if inspect.ismethod(f):
        new = transform_func(f.__func__)
        if new is not f.__func__:
            return types.MethodType(new, f.__self__)
        return f
    if inspect.isfunction(f):
        return transform_func(f)
    return f


def _transform_layer_forward(layer):
    fwd = getattr(layer, "forward", None)
    if fwd is None or not inspect.ismethod(fwd):
        return
    if getattr(fwd.__func__, "_ptd2s_transformed", False) or \
            getattr(fwd.__func__, "_not_to_static", False):
        return
    new = transform_func(fwd.__func__)
    if new is not fwd.__func__:
        layer.forward = types.MethodType(new, layer)


def convert_to_static(function):
    """Entry used by to_static: convert the top-level traced function."""
    if inspect.ismethod(function):
        new = transform_func(function.__func__)
        if new is not function.__func__:
            return types.MethodType(new, function.__self__)
        return function
    if inspect.isfunction(function):
        return transform_func(function)
    return function


def transform_func(fn):
    """AST-convert one plain function; return it unchanged on any failure."""
    cached = getattr(fn, "_ptd2s_variant", None)
    if cached is not None:
        return cached
    if fn in _fail_cache or getattr(fn, "_ptd2s_transformed", False):
        return fn
    mod_root = (getattr(fn, "__module__", "") or "").split(".")[0]
    if mod_root in _SKIP_MODULE_ROOTS:
        _fail_cache.add(fn)
        return fn
    if fn.__code__.co_flags & (inspect.CO_GENERATOR | inspect.CO_COROUTINE |
                               inspect.CO_ASYNC_GENERATOR):
        _fail_cache.add(fn)
        return fn
    if fn.__name__ == "<lambda>":
        _fail_cache.add(fn)
        return fn
    # re-entrancy guard (recursive defs)
    if getattr(_local, "in_progress", None) is None:
        _local.in_progress = set()
    key = (fn.__module__, fn.__qualname__)
    if key in _local.in_progress:
        return fn
    _local.in_progress.add(key)
    # one span + counter per first-use conversion: AST transforms are a
    # one-time trace-path cost, but a hot loop that defeats the
    # _ptd2s_variant cache shows up here immediately.  Telemetry is
    # entered/exited OUTSIDE the fail-cache try: a telemetry error must
    # not discard a successful transform or fail-cache the function.
    _span_cm = None
    try:
        from paddle_tpu.observability import span as _obs_span
        _span_cm = _obs_span("dy2static.transform", fn=fn.__qualname__)
        _span_cm.__enter__()
    except Exception:
        _span_cm = None
    try:
        new = _do_transform(fn)
    except Exception as e:
        from paddle_tpu.analysis.rules import TraceHazardError
        if isinstance(e, TraceHazardError):
            # conversion-time rejections (TL005 identity-test hole)
            # must surface to the user, not fall back to plain Python
            # — the fallback is exactly the silent-wrong-branch hazard
            raise
        _fail_cache.add(fn)
        return fn
    finally:
        _local.in_progress.discard(key)
        if _span_cm is not None:
            try:
                _span_cm.__exit__(None, None, None)
            except Exception:
                pass
    try:
        from paddle_tpu.observability import metrics as _obs_metrics
        _obs_metrics.registry().counter(
            "dy2static_transforms_total",
            help="functions AST-converted by dy2static").inc()
    except Exception:
        pass
    try:
        fn._ptd2s_variant = new
    except (AttributeError, TypeError):
        pass
    return new


def _do_transform(fn):
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef,)):
        raise TypeError("not a plain def")
    fdef.decorator_list = []
    _check_identity_tests(fdef, fn.__code__.co_filename,
                          fn.__code__.co_firstlineno,
                          src.splitlines())

    # pre-passes: normalize guard-clause early returns into the
    # both-branches-return form, then desugar break/continue into
    # guard flags (see the module docstring)
    ret_changed = [False]
    fdef.body = _normalize_returns(fdef.body, [], ret_changed)
    desugar = _ExitDesugar()
    fdef.body = desugar.block(fdef.body)

    bound = _function_bound_names(fdef)
    tr = _Transformer(bound, src_info=(fn.__code__.co_filename,
                                       fn.__code__.co_firstlineno))
    tr.changed = ret_changed[0] or desugar.changed
    # visit the BODY, not fdef itself — the transformer's
    # visit_FunctionDef is a no-descend guard for nested scopes
    new_body = []
    for s in fdef.body:
        r = tr.visit(s)
        if isinstance(r, list):
            new_body.extend(r)
        elif r is not None:
            new_body.append(r)
    fdef.body = new_body
    if not tr.changed:
        # nothing convertible: keep the original (zero overhead)
        fn._ptd2s_transformed = True
        return fn
    ast.fix_missing_locations(tree)

    filename = f"<dy2static {fn.__module__}.{fn.__qualname__}>"
    code = compile(tree, filename, "exec")
    new_src = None
    try:
        new_src = ast.unparse(tree)
        linecache.cache[filename] = (
            len(new_src), None, new_src.splitlines(True), filename)
    except Exception:
        pass

    import paddle_tpu.jit.dy2static as _me
    if fn.__closure__:
        # freevars force a private namespace: cell values are snapshotted
        # at transform time (rebinding a closed-over variable afterwards
        # is invisible to the converted function — documented limitation,
        # same tradeoff as the reference's exec-based retransform)
        g = dict(fn.__globals__)
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                g[name] = cell.cell_contents
            except ValueError:
                pass
        g["_ptd2s"] = _me
    else:
        # no freevars: exec against the LIVE module globals so later
        # rebinding of module-level names (flags, schedules, models)
        # stays visible, exactly as in the untransformed function
        g = fn.__globals__
        g.setdefault("_ptd2s", _me)
    ns = {}
    exec(code, g, ns)
    new = ns[fdef.name]
    new.__wrapped__ = fn
    new._ptd2s_transformed = True
    new.__defaults__ = fn.__defaults__
    new.__kwdefaults__ = fn.__kwdefaults__
    return new


def _same_scope_walk(stmts):
    """ast.walk over a statement list that does NOT descend into nested
    scopes (defs/lambdas/classes own their names)."""
    stack = list(stmts)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _check_identity_tests(fdef, src_file, src_base, src_lines=()):
    """Conversion-time rejection of the `is`-operator poison-sentinel
    hole: a name bound in only ONE branch of an `if` (and nowhere
    before it) merges to the UNDEF sentinel under conversion, and a
    later identity test (`name is None`) is the one read the sentinel
    cannot intercept — it would silently compare the sentinel object.
    Detected here, on the ORIGINAL AST, as a named ``TraceHazardError``
    (TL005) instead: the fix (bind on every path) is cheap and the
    silent-wrong-branch failure is not.

    Scope-approximation contract: a store anywhere EARLIER in source
    order counts as "bound before" (mis-approximations err toward NOT
    flagging), and a rebind between the `if` and the identity test
    clears the hazard.  The check is deliberately conservative (it
    cannot see that a short-circuit guard makes a particular read
    safe), so a ``# tracelint: disable=TL005`` comment on the identity
    test's line waives it — the same suppression spelling every other
    TL rule honors."""
    # the ONE suppression parser every analyzer shares — same
    # lowercase/alias/skip-file semantics as file-level tracelint
    from paddle_tpu.analysis.visitor import parse_suppressions
    sup, skip_file = parse_suppressions("\n".join(src_lines))
    if skip_file:
        return

    def suppressed(lineno):
        codes = sup.get(lineno, ())
        return "TL005" in codes or "ALL" in codes
    a = fdef.args
    params = {arg.arg for arg in (
        a.posonlyargs + a.args + a.kwonlyargs
        + ([a.vararg] if a.vararg else [])
        + ([a.kwarg] if a.kwarg else []))}
    stores = {}          # name -> sorted store linenos (same scope)
    for n in _same_scope_walk(fdef.body):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            stores.setdefault(n.id, []).append(n.lineno)
        elif isinstance(n, ast.ExceptHandler) and n.name:
            stores.setdefault(n.name, []).append(n.lineno)
        elif isinstance(n, (ast.Import, ast.ImportFrom)):
            for al in n.names:
                stores.setdefault(
                    (al.asname or al.name).split(".")[0],
                    []).append(n.lineno)
    compares = []        # (node, names, lineno)
    for n in _same_scope_walk(fdef.body):
        if isinstance(n, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
            names = {x.id for x in ast.walk(n)
                     if isinstance(x, ast.Name)
                     and isinstance(x.ctx, ast.Load)}
            if names:
                compares.append((n, names))
    if not compares:
        return
    for node in _same_scope_walk(fdef.body):
        if not isinstance(node, ast.If):
            continue
        b = _collect_bound(node.body)
        o = _collect_bound(node.orelse)
        maybe = (b | o) - (b & o)
        if not maybe:
            continue
        before = params | {nm for nm, lns in stores.items()
                           if any(ln < node.lineno for ln in lns)}
        maybe -= before
        if not maybe:
            continue
        end = max((x.lineno for x in ast.walk(node)
                   if hasattr(x, "lineno")), default=node.lineno)
        for cmp_node, names in compares:
            if cmp_node.lineno <= end:
                continue     # inside (or before) the if itself
            if suppressed(cmp_node.lineno):
                continue
            bad = sorted(
                nm for nm in names & maybe
                # a rebind between the if and the test clears it
                if not any(end < ln < cmp_node.lineno
                           for ln in stores.get(nm, ())))
            if bad:
                from paddle_tpu.analysis.rules import TraceHazardError
                raise TraceHazardError(
                    "TL005", src_file, src_base + cmp_node.lineno - 1,
                    detail=f"`{bad[0]}`")


def _function_bound_names(fdef):
    names = set()
    a = fdef.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs +
                ([a.vararg] if a.vararg else []) +
                ([a.kwarg] if a.kwarg else [])):
        names.add(arg.arg)
    names |= _collect_bound(fdef.body)
    return names


def _collect_bound(stmts):
    """Names bound by a statement list, same scope only (skip nested defs
    and our generated helpers)."""
    out = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            if not node.name.startswith(_GEN_PREFIX):
                out.add(node.name)
            # do not descend: inner scope

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            out.add(node.name)

        def visit_Lambda(self, node):
            pass

        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)) and \
                    not node.id.startswith(_GEN_PREFIX):
                out.add(node.id)

        def visit_Import(self, node):
            for al in node.names:
                out.add((al.asname or al.name).split(".")[0])

        visit_ImportFrom = visit_Import

        def visit_Nonlocal(self, node):
            out.update(n for n in node.names
                       if not n.startswith(_GEN_PREFIX))

        def visit_Global(self, node):
            pass

    v = V()
    for s in stmts:
        v.visit(s)
    return out


def _exit_in_finally(stmts):
    """Does a break/continue belonging to THIS loop level sit inside a
    ``try``'s ``finally`` block?  Such loops cannot flag-lower: a real
    exit in ``finally`` runs during exception unwind (and swallows the
    in-flight exception); the flag form cannot reproduce either, so the
    loop stays plain Python."""
    found = [False]

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef

        def visit_While(self, node):
            pass            # a nested loop owns its exits

        visit_For = visit_While

        def visit_Try(self, node):
            if _contains(node.finalbody, (ast.Break, ast.Continue),
                         stop_at_loops=True):
                found[0] = True
            self.generic_visit(node)

    for s in stmts:
        V().visit(s)
    return found[0]


def _exit_in_unhandled(stmts):
    """Is a this-loop-level ``break``/``continue`` nested under a
    statement type :meth:`_ExitDesugar._rewrite` does not descend
    (e.g. ``match``)?  Such loops must stay plain Python: lowering them
    would leave the raw exit inside the counter-while form, where a
    ``continue`` skips the counter increment — an infinite trace-time
    hang.  Deny-by-default: only the containers _rewrite provably
    handles (If / With / Try) are walked; anything else containing an
    exit keeps the loop unconverted."""
    for s in stmts:
        if isinstance(s, (ast.Break, ast.Continue)):
            continue                     # this level: _rewrite handles it
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue                     # different exit owner
        if isinstance(s, (ast.While, ast.For, ast.AsyncFor)):
            # the nested loop's BODY owns its exits, but its `else:`
            # clause runs in OUR scope — and _rewrite never descends
            # nested loops, so any exit there is unhandled
            if _contains(s.orelse, (ast.Break, ast.Continue),
                         stop_at_loops=True):
                return True
            continue
        if isinstance(s, ast.If):
            if _exit_in_unhandled(s.body) or _exit_in_unhandled(s.orelse):
                return True
        elif isinstance(s, ast.With):
            if _exit_in_unhandled(s.body):
                return True
        elif isinstance(s, ast.Try):
            # finalbody exits already keep the loop plain (_exit_in_finally)
            if _exit_in_unhandled(s.body) or _exit_in_unhandled(s.orelse):
                return True
            for h in s.handlers:
                if _exit_in_unhandled(h.body):
                    return True
        elif _contains([s], (ast.Break, ast.Continue), stop_at_loops=True):
            return True
    return False


def _contains(stmts, kinds, stop_at_loops=False):
    """Does any statement (same function scope) contain a node of `kinds`?
    With stop_at_loops, break/continue inside NESTED loops don't count."""
    found = [False]

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef

        def _visit_loop(self, node, header_fields):
            if stop_at_loops:
                # the nested loop's body owns its break/continue; only
                # its header expressions and orelse are OUR scope
                for f in header_fields:
                    self.visit(getattr(node, f))
                for s in node.orelse:
                    self.visit(s)
                if any(kind is ast.Return for kind in kinds):
                    for s in node.body:  # returns still escape nested loops
                        for n in ast.walk(s):
                            if isinstance(n, ast.Return):
                                found[0] = True
            else:
                self.generic_visit(node)

        def visit_While(self, node):
            self._visit_loop(node, ("test",))

        def visit_For(self, node):
            self._visit_loop(node, ("target", "iter"))

        def generic_visit(self, node):
            if isinstance(node, kinds):
                found[0] = True
            super().generic_visit(node)

    v = V()
    for s in stmts:
        v.visit(s)
    return found[0]


def _is_guard(node):
    return (isinstance(node, ast.Try) and len(node.handlers) == 1 and
            len(node.body) == 1 and isinstance(node.body[0], ast.Expr) and
            isinstance(node.body[0].value, ast.Name))


def _store_first(stmts, names):
    """Subset of `names` whose first access in `stmts` (execution order,
    same scope, skipping UNDEF guards) is a plain STORE — per-iteration
    temporaries when applied to a loop body."""
    status = {}

    def mark(n, kind):
        if n in names and n not in status:
            status[n] = kind

    def visit(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return
        if _is_guard(node):
            return
        if isinstance(node, ast.Assign):
            visit(node.value)
            for t in node.targets:
                visit(t)
            return
        if isinstance(node, ast.AugAssign):
            visit(node.value)
            if isinstance(node.target, ast.Name):
                mark(node.target.id, "load")  # read-modify-write
            visit(node.target)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                visit(node.value)
            visit(node.target)
            return
        if isinstance(node, ast.Name):
            mark(node.id,
                 "store" if isinstance(node.ctx, ast.Store) else "load")
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    for s in stmts:
        visit(s)
    return {n for n in names if status.get(n) == "store"}


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


# ---------------- pre-passes: early return + break/continue desugaring
def _shallow_has_return(stmts):
    """Return present at this control level (descends ifs/try, NOT
    loops or nested defs — a loop owns its returns and stays plain)."""
    for s in stmts:
        for n in _walk_no_loops(s):
            if isinstance(n, ast.Return):
                return True
    return False


def _walk_no_loops(node):
    """ast.walk that does not descend into loops or nested scopes."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.While, ast.For, ast.FunctionDef,
                          ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _terminates(stmts):
    """Every execution path through `stmts` ends in `return`."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        return (_terminates(last.body) and bool(last.orelse)
                and _terminates(last.orelse))
    return False


def _normalize_returns(stmts, tail, changed):
    """Equivalent of `stmts` followed by `tail`, with guard-clause early
    returns rewritten so both branches of the `if` end in `return`
    (reference return/early_return transformers; here the select-form
    `convert_ifelse_ret` then merges the two return values). Only the
    duplication-free cases transform: the returning branch must return
    on ALL its paths, so the trailing statements move into the OTHER
    branch exactly once."""
    out = []
    for k, s in enumerate(stmts):
        if isinstance(s, ast.If):
            rest = stmts[k + 1:]
            b_ret = _terminates(s.body)
            o_ret = bool(s.orelse) and _terminates(s.orelse)
            if (_shallow_has_return(s.body)
                    or _shallow_has_return(s.orelse)) and (b_ret or o_ret):
                if b_ret and o_ret:
                    # both branches return on every path: the tail is
                    # unreachable and drops
                    new = ast.If(
                        test=s.test,
                        body=_normalize_returns(s.body, [], changed),
                        orelse=_normalize_returns(s.orelse, [], changed))
                elif b_ret:
                    changed[0] = True
                    new = ast.If(
                        test=s.test,
                        body=_normalize_returns(s.body, [], changed),
                        orelse=_normalize_returns(
                            s.orelse + rest, tail, changed))
                else:
                    changed[0] = True
                    new = ast.If(
                        test=s.test,
                        body=_normalize_returns(
                            s.body + rest, tail, changed),
                        orelse=_normalize_returns(s.orelse, [], changed))
                ast.copy_location(new, s)
                out.append(new)
                return out
        out.append(s)
    out.extend(tail)
    return out


class _ExitDesugar:
    """break/continue -> guard flags (reference
    break_continue_transformer.py): the flags become ordinary locals
    (`_d2s_v_*`, loop carries under a tensor-converted while), the
    statements after a flag-set are wrapped in `if not flag:`, and the
    loop condition gains `not brk and ...`. For-range loops with a
    break lower to the explicit counter-while form here (same lowering
    visit_For performs) so the test can carry the flag."""

    def __init__(self):
        self.n = 0
        self.changed = False

    def block(self, stmts):
        """Desugar every loop in a statement list (recursing into ifs
        and nested defs are skipped — they desugar on their own)."""
        out = []
        for s in stmts:
            out.extend(self.stmt(s))
        return out

    def stmt(self, s):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            return [s]
        if isinstance(s, (ast.While, ast.For)):
            return self.loop(s)
        if isinstance(s, ast.If):
            new = ast.If(test=s.test, body=self.block(s.body) or [ast.Pass()],
                         orelse=self.block(s.orelse))
            return [ast.copy_location(new, s)]
        if isinstance(s, ast.Try):
            new = ast.Try(
                body=self.block(s.body),
                handlers=[ast.ExceptHandler(type=h.type, name=h.name,
                                            body=self.block(h.body))
                          for h in s.handlers],
                orelse=self.block(s.orelse),
                finalbody=self.block(s.finalbody))
            return [ast.copy_location(new, s)]
        if isinstance(s, ast.With):
            new = ast.With(items=s.items, body=self.block(s.body))
            return [ast.copy_location(new, s)]
        return [s]

    def loop(self, node):
        has_exit = _contains(node.body, (ast.Break, ast.Continue),
                             stop_at_loops=True)
        has_ret = _contains(node.body, (ast.Return,))
        if not has_exit or has_ret or node.orelse or \
                _exit_in_finally(node.body) or \
                _exit_in_unhandled(node.body):
            # no exits to desugar — or a return makes the loop
            # unconvertible anyway (left plain; visit_While/For bail).
            # break/continue inside a `finally` stays plain too: a real
            # exit there runs DURING exception unwind (and may swallow
            # the exception); the flag form cannot reproduce that.
            # Same for exits under statement types _rewrite does not
            # descend (match, ...): lowering would leave the raw exit in
            # the counter-while form — the trace-time-hang class
            body = self.block(node.body)
            new = type(node)(**{**{f: getattr(node, f)
                                   for f in node._fields}, "body": body})
            return [ast.copy_location(new, node)]

        self.n += 1
        self.changed = True
        i = self.n
        brk = f"_d2s_v_brk_{i}"
        cont = f"_d2s_v_cont_{i}"
        used_cont = _contains(node.body, (ast.Continue,), stop_at_loops=True)
        used_brk = _contains(node.body, (ast.Break,), stop_at_loops=True)

        body, _ = self._rewrite(self.block(node.body), brk, cont,
                                used_brk, used_cont)
        pre = []
        if used_cont:
            # per-iteration flag: reset at body top, never carried
            body = [_assign(cont, False)] + body
        if used_brk:
            pre.append(_assign(brk, False))

        if isinstance(node, ast.While):
            test = node.test
            if used_brk:
                test = ast.BoolOp(op=ast.And(), values=[
                    ast.UnaryOp(op=ast.Not(), operand=_nm(brk)), test])
            new = ast.While(test=test, body=body, orelse=[])
            return pre + [ast.copy_location(new, node)]

        # For: only `for <name> in range(...)` desugars (same subset
        # visit_For converts); anything else keeps its raw break and
        # stays plain Python
        it = node.iter
        is_range = (isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Name)
                    and it.func.id == "range" and not it.keywords
                    and 1 <= len(it.args) <= 3
                    and isinstance(node.target, ast.Name))
        if not is_range:
            # keep the raw break (plain-Python loop) but still desugar
            # any loops nested deeper
            new = ast.For(target=node.target, iter=node.iter,
                          body=self.block(node.body), orelse=[])
            return [ast.copy_location(new, node)]
        # `vd` namespace: visit_For independently numbers its own
        # `_d2s_v_i_*` counters — a shared prefix collided (the inner
        # desugared loop's make_range overwrote the outer counter)
        ctr, stop, step = (f"_d2s_vd_{k}_{i}" for k in ("i", "stop", "step"))
        setup = ast.Assign(
            targets=[ast.Tuple(elts=[_nm(ctr, ast.Store()),
                                     _nm(stop, ast.Store()),
                                     _nm(step, ast.Store())],
                               ctx=ast.Store())],
            value=ast.Call(func=_ptd2s_attr("make_range"),
                           args=list(it.args), keywords=[]))
        test = ast.Call(func=_ptd2s_attr("range_cond"),
                        args=[_nm(ctr), _nm(stop), _nm(step)], keywords=[])
        if used_brk:
            test = ast.BoolOp(op=ast.And(), values=[
                ast.UnaryOp(op=ast.Not(), operand=_nm(brk)), test])
        bind = _assign_name(node.target.id, _nm(ctr))
        # the counter increment is LOOP MACHINERY: it sits outside the
        # continue guard (python's `for` advances the iterator on
        # continue) and runs even on the break iteration (the flag, not
        # the counter, ends the loop)
        inc = ast.Assign(targets=[_nm(ctr, ast.Store())],
                         value=ast.BinOp(left=_nm(ctr), op=ast.Add(),
                                         right=_nm(step)))
        new = ast.While(test=test, body=[bind] + body + [inc], orelse=[])
        return pre + [ast.copy_location(setup, node),
                      ast.copy_location(new, node)]

    def _flag_guard(self, body, used_brk, used_cont, brk, cont, loc):
        """`if not (brk or cont): <body>` — the wrapper for statements
        that must not run once an exit flag may have been set."""
        flags = ([_nm(brk)] if used_brk else []) + \
                ([_nm(cont)] if used_cont else [])
        test = flags[0] if len(flags) == 1 else \
            ast.BoolOp(op=ast.Or(), values=flags)
        guard = ast.If(test=ast.UnaryOp(op=ast.Not(), operand=test),
                       body=body, orelse=[])
        return ast.copy_location(guard, loc)

    def _guard_rest(self, out, rest_stmts, brk, cont, used_brk,
                    used_cont, loc):
        rest, _ = self._rewrite(rest_stmts, brk, cont, used_brk,
                                used_cont)
        if rest:
            out.append(self._flag_guard(rest, used_brk, used_cont,
                                        brk, cont, loc))

    def _rewrite(self, stmts, brk, cont, used_brk, used_cont):
        """Replace break/continue at THIS loop level with flag sets and
        guard-wrap the statements that follow a possible set — descending
        into If, With, and Try (body/handlers/orelse; `finally` never
        holds exits here, loop() keeps those loops plain). Returns
        (stmts, may_set_flag)."""
        out = []
        for k, s in enumerate(stmts):
            if isinstance(s, ast.Break):
                out.append(ast.copy_location(_assign(brk, True), s))
                return out, True            # rest of the list is dead
            if isinstance(s, ast.Continue):
                out.append(ast.copy_location(_assign(cont, True), s))
                return out, True
            if isinstance(s, ast.If):
                b, bf = self._rewrite(s.body, brk, cont,
                                      used_brk, used_cont)
                o, of = self._rewrite(s.orelse, brk, cont,
                                      used_brk, used_cont)
                s = ast.copy_location(
                    ast.If(test=s.test, body=b or [ast.Pass()], orelse=o),
                    s)
                if bf or of:
                    out.append(s)
                    self._guard_rest(out, stmts[k + 1:], brk, cont,
                                     used_brk, used_cont, s)
                    return out, True
                out.append(s)
                continue
            if isinstance(s, ast.With):
                # an exit inside `with` leaves the block normally (the
                # __exit__ still runs at block end), so the flag-set +
                # guarded-tail form is exact
                b, bf = self._rewrite(s.body, brk, cont,
                                      used_brk, used_cont)
                s = ast.copy_location(
                    ast.With(items=s.items, body=b or [ast.Pass()]), s)
                if bf:
                    out.append(s)
                    self._guard_rest(out, stmts[k + 1:], brk, cont,
                                     used_brk, used_cont, s)
                    return out, True
                out.append(s)
                continue
            if isinstance(s, ast.Try):
                b, bf = self._rewrite(s.body, brk, cont,
                                      used_brk, used_cont)
                handlers, hf = [], False
                for h in s.handlers:
                    hb, f = self._rewrite(h.body, brk, cont,
                                          used_brk, used_cont)
                    hf = hf or f
                    handlers.append(ast.ExceptHandler(
                        type=h.type, name=h.name,
                        body=hb or [ast.Pass()]))
                o, of = self._rewrite(s.orelse, brk, cont,
                                      used_brk, used_cont)
                if bf and o:
                    # a real exit in the try body skips `else`; after
                    # flag-lowering the Try completes "normally", so the
                    # else must be explicitly flag-guarded
                    o = [self._flag_guard(o, used_brk, used_cont,
                                          brk, cont, s)]
                s = ast.copy_location(
                    ast.Try(body=b or [ast.Pass()], handlers=handlers,
                            orelse=o, finalbody=s.finalbody), s)
                if bf or hf or of:
                    out.append(s)
                    self._guard_rest(out, stmts[k + 1:], brk, cont,
                                     used_brk, used_cont, s)
                    return out, True
                out.append(s)
                continue
            out.append(s)
        return out, False


def _assign(name, const):
    return ast.Assign(targets=[_nm(name, ast.Store())],
                      value=ast.Constant(value=const))


def _assign_name(name, value):
    return ast.Assign(targets=[_nm(name, ast.Store())], value=value)


def _nm(n, ctx=None):
    return ast.Name(id=n, ctx=ctx or ast.Load())


def _ptd2s_attr(name):
    return ast.Attribute(value=_nm("_ptd2s"), attr=name, ctx=ast.Load())


def _undef_guard(n):
    return ast.Try(
        body=[ast.Expr(value=_nm(n))],
        handlers=[ast.ExceptHandler(
            type=_nm("NameError"), name=None,
            body=[ast.Assign(targets=[_nm(n, ast.Store())],
                             value=_ptd2s_attr("UNDEF"))])],
        orelse=[], finalbody=[])


def _tuple_expr(names, ctx=None):
    ctx = ctx or ast.Load()
    return ast.Tuple(elts=[_nm(n, type(ctx)()) for n in names], ctx=ctx)


def _def(name, body, params=()):
    a = _empty_args()
    a.args = [ast.arg(arg=p) for p in params]
    return ast.FunctionDef(name=name, args=a, body=body,
                           decorator_list=[], returns=None)


class _Transformer(ast.NodeTransformer):
    def __init__(self, fn_bound_names, src_info=("<unknown>", 1)):
        self.bound = set(fn_bound_names)
        self.changed = False
        self.n = 0
        self.src_file, self.src_base = src_info

    def _next(self):
        self.n += 1
        return self.n

    def _guard(self, expr, code, node):
        """Wrap a loop-header expression of a loop LEFT PLAIN so a traced
        value raises the named tracelint diagnostic (rule `code`) with
        the ORIGINAL file:line instead of a concretization error."""
        self.changed = True
        lineno = self.src_base + getattr(node, "lineno", 1) - 1
        return ast.Call(
            func=_ptd2s_attr("guard_unconvertible"),
            args=[expr, ast.Constant(value=code),
                  ast.Constant(value=self.src_file),
                  ast.Constant(value=lineno)],
            keywords=[])

    # -- do not descend into nested scopes --
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    # ---- boolean operators ----
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        self.changed = True
        fn = "convert_logical_and" if isinstance(node.op, ast.And) \
            else "convert_logical_or"
        lambdas = [ast.Lambda(args=_empty_args(), body=v)
                   for v in node.values]
        return ast.Call(func=_ptd2s_attr(fn), args=lambdas, keywords=[])

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            self.changed = True
            return ast.Call(func=_ptd2s_attr("convert_logical_not"),
                            args=[node.operand], keywords=[])
        return node

    # ---- call-site wrapping ----
    def visit_Call(self, node):
        self.generic_visit(node)
        f = node.func
        if isinstance(f, ast.Name) and (f.id in _NO_WRAP_NAMES or
                                        f.id.startswith(_GEN_PREFIX)):
            return node
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "_ptd2s":
            return node
        self.changed = True
        node.func = ast.Call(func=_ptd2s_attr("convert_call"), args=[f],
                             keywords=[])
        return node

    # ---- if / elif / else ----
    def visit_If(self, node):
        self.generic_visit(node)
        body, orelse = node.body, node.orelse

        def last_is_return(stmts):
            return bool(stmts) and isinstance(stmts[-1], ast.Return)

        has_ret_b = _contains(body, (ast.Return,))
        has_ret_o = _contains(orelse, (ast.Return,))
        has_brk = _contains(body + orelse, (ast.Break, ast.Continue),
                            stop_at_loops=True)
        if has_brk:
            return node  # leave: converting would break loop control flow

        if has_ret_b or has_ret_o:
            # supported return form: BOTH branches are straight-line code
            # ending in `return` (no other returns)
            def only_last_returns(stmts):
                if not last_is_return(stmts):
                    return False
                return not _contains(stmts[:-1], (ast.Return,))

            if not (only_last_returns(body) and only_last_returns(orelse)):
                return node  # early-exit patterns stay plain Python
            i = self._next()
            tname, fname = f"{_GEN_PREFIX}t_{i}", f"{_GEN_PREFIX}f_{i}"
            stmts = []
            guard_names = set()
            for name, branch in ((tname, body), (fname, orelse)):
                assigned = _collect_bound(branch)
                nl = sorted(assigned & self.bound)
                # a branch-local name may have NO enclosing binding
                # (e.g. bound only inside this branch after return
                # normalization pushed it here): the UNDEF guard
                # creates one so `nonlocal` is legal
                guard_names.update(nl)
                b = ([ast.Nonlocal(names=nl)] if nl else []) + branch
                stmts.append(_def(name, b))
            stmts = [_undef_guard(n) for n in sorted(guard_names)] + stmts
            self.changed = True
            ret = ast.Return(value=ast.Call(
                func=_ptd2s_attr("convert_ifelse_ret"),
                args=[node.test, _nm(tname), _nm(fname)], keywords=[]))
            return stmts + [ret]

        modified = sorted((_collect_bound(body) | _collect_bound(orelse)))
        i = self._next()
        g, s_, t, f = (f"{_GEN_PREFIX}{k}_{i}" for k in "gstf")
        guards = [_undef_guard(n) for n in modified]
        get_def = _def(g, [ast.Return(value=_tuple_expr(modified))])
        set_body = []
        if modified:
            set_body = [ast.Nonlocal(names=modified),
                        ast.Assign(targets=[_tuple_expr(modified,
                                                        ast.Store())],
                                   value=_nm("__v"))]
        else:
            set_body = [ast.Pass()]
        set_def = _def(s_, set_body, params=("__v",))
        nl = [ast.Nonlocal(names=modified)] if modified else []
        t_def = _def(t, nl + (body or [ast.Pass()]))
        f_def = _def(f, list(nl) + (orelse or [ast.Pass()]))
        call = ast.Call(func=_ptd2s_attr("convert_ifelse"),
                        args=[node.test, _nm(t), _nm(f), _nm(g), _nm(s_)],
                        keywords=[])
        if modified:
            out = ast.Assign(targets=[_tuple_expr(modified, ast.Store())],
                             value=call)
            self.bound.update(modified)
        else:
            out = ast.Expr(value=call)
        self.changed = True
        return guards + [get_def, set_def, t_def, f_def, out]

    # ---- while ----
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            node.test = self._guard(node.test, "TL003", node)
            return node
        if _contains(node.body, (ast.Return,)):
            node.test = self._guard(node.test, "TL001", node)
            return node
        if _contains(node.body, (ast.Break, ast.Continue),
                     stop_at_loops=True):
            # break/continue the desugarer could not lift (e.g. mixed
            # with a return elsewhere) — same unconvertible bucket
            node.test = self._guard(node.test, "TL001", node)
            return node
        modified = sorted(_collect_bound(node.body))
        i = self._next()
        g, s_, c, b = (f"{_GEN_PREFIX}{k}_{i}" for k in ("g", "s", "c", "b"))
        guards = [_undef_guard(n) for n in modified]
        get_def = _def(g, [ast.Return(value=_tuple_expr(modified))])
        if modified:
            set_body = [ast.Nonlocal(names=modified),
                        ast.Assign(targets=[_tuple_expr(modified,
                                                        ast.Store())],
                                   value=_nm("__v"))]
        else:
            set_body = [ast.Pass()]
        set_def = _def(s_, set_body, params=("__v",))
        c_def = _def(c, [ast.Return(value=node.test)])
        nl = [ast.Nonlocal(names=modified)] if modified else []
        b_def = _def(b, nl + (node.body or [ast.Pass()]))
        test_reads = {x.id for x in ast.walk(node.test)
                      if isinstance(x, ast.Name) and
                      isinstance(x.ctx, ast.Load)}
        temps = _store_first(node.body, set(modified)) - test_reads
        temp_mask = ast.Tuple(
            elts=[ast.Constant(value=(nme in temps)) for nme in modified],
            ctx=ast.Load())
        call = ast.Call(func=_ptd2s_attr("convert_while"),
                        args=[_nm(c), _nm(b), _nm(g), _nm(s_), temp_mask],
                        keywords=[])
        if modified:
            out = ast.Assign(targets=[_tuple_expr(modified, ast.Store())],
                             value=call)
            self.bound.update(modified)
        else:
            out = ast.Expr(value=call)
        self.changed = True
        return guards + [get_def, set_def, c_def, b_def, out]

    # ---- for i in range(...) -> while ----
    def visit_For(self, node):
        if node.orelse or not isinstance(node.target, ast.Name):
            self.generic_visit(node)
            return node
        it = node.iter
        is_range = (isinstance(it, ast.Call) and
                    isinstance(it.func, ast.Name) and it.func.id == "range"
                    and not it.keywords and 1 <= len(it.args) <= 3)
        if not is_range:
            self.generic_visit(node)
            return node
        if _contains(node.body, (ast.Return,)) or \
                _contains(node.body, (ast.Break, ast.Continue),
                          stop_at_loops=True):
            self.generic_visit(node)
            # range(tensor) on a plain-Python loop concretizes via
            # __index__ — guard each range operand so a traced bound
            # raises the named TL001 diagnostic instead
            it = node.iter
            it.args = [self._guard(a, "TL001", node) for a in it.args]
            return node
        i = self._next()
        # generated VARIABLES use a non-helper prefix so the while
        # transformer treats them as ordinary locals (the counter must
        # be a loop CARRY; helper-def names stay excluded via
        # _GEN_PREFIX). The counter is hidden — the loop body may freely
        # clobber the user target (Python's `for` iterator state is
        # independent of the target binding; nested fors reusing one
        # target name were miscounting when the target WAS the state)
        ctr = f"_d2s_v_i_{i}"
        stop = f"_d2s_v_stop_{i}"
        step = f"_d2s_v_step_{i}"
        tgt = node.target.id
        setup = ast.Assign(
            targets=[ast.Tuple(elts=[_nm(ctr, ast.Store()),
                                     _nm(stop, ast.Store()),
                                     _nm(step, ast.Store())],
                               ctx=ast.Store())],
            value=ast.Call(func=_ptd2s_attr("make_range"),
                           args=list(it.args), keywords=[]))
        test = ast.Call(func=_ptd2s_attr("range_cond"),
                        args=[_nm(ctr), _nm(stop), _nm(step)],
                        keywords=[])
        bind_tgt = ast.Assign(targets=[_nm(tgt, ast.Store())],
                              value=_nm(ctr))
        inc = ast.Assign(targets=[_nm(ctr, ast.Store())],
                         value=ast.BinOp(left=_nm(ctr), op=ast.Add(),
                                         right=_nm(step)))
        loop = ast.While(test=test, body=[bind_tgt] + node.body + [inc],
                         orelse=[])
        self.bound.update({tgt, ctr, stop, step})
        self.changed = True
        out = self.visit_While(loop)
        return [setup] + (out if isinstance(out, list) else [out])
