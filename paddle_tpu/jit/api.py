"""paddle_tpu.jit.to_static — whole-program XLA compilation.

Reference parity: python/paddle/jit/dy2static (Dy2Static ProgramTranslator):
the reference AST-transforms dygraph code into a ProgramDesc graph executed by
the fluid executor. TPU-native redesign: we TRACE the user's imperative
function (model forward, `loss.backward()`, `opt.step()` — all of it) with JAX
tracers. Every framework-mutable tensor (Parameters, buffers, optimizer
accumulators, the RNG key, the LR scalar) is lifted from the global state
registry into pytree inputs, and their post-trace values are returned as
outputs — a pure function compiled ONCE by XLA per input signature. State
arrays are donated so XLA updates parameters in place (no HBM copies).

This is the TPU-native analogue of the whole-graph executor: one fused XLA
program per step instead of per-op kernel dispatch.
"""
from __future__ import annotations

import inspect
import threading
import time

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework import state as fstate
from paddle_tpu.observability import recompile as _obs_recompile
from paddle_tpu.observability import span as _span

_tree = jax.tree_util

_trace_state = threading.local()


class _CompiledEntry(__import__("typing").NamedTuple):
    """One compiled signature of a StaticFunction. Field access, not
    positional unpacking, is the supported way to consume this (the
    3->4-tuple growth broke five positional unpackers at once)."""

    jitted: object
    out_info: object
    state_list: list
    grad_idx: tuple
    # uids whose grads the traced fn CLEARED (clear_grad) during the
    # step: their materialized grads overwrite param.grad; all others
    # accumulate onto whatever .grad held before the call — matching
    # what the same fn does in eager mode (the traced program always
    # starts grads at None, so its grad outputs are per-step deltas)
    grad_cleared: frozenset = frozenset()


def _in_to_static_trace():
    return getattr(_trace_state, "active", False)


def _audit_input_infos(state_list, tensor_vals):
    """InputInfos for one traced signature's jaxpr invars: the lifted
    state tensors then the user tensor args.  ONE builder for both the
    audit=True hook and traced_program, so the same defect fingerprints
    identically no matter which path found it."""
    from paddle_tpu import analysis
    infos = analysis.input_infos_from_state(state_list)
    for i, v in enumerate(tensor_vals):
        infos.append(analysis.InputInfo(
            name=f"arg{i}", kind="input", shape=tuple(v.shape),
            dtype=str(v.dtype), nbytes=int(getattr(v, "nbytes", 0) or 0)))
    return infos


def note_grad_cleared(uid):
    """Called by Tensor.clear_grad: records, during a to_static trace,
    that the step clears this tensor's grad (see _CompiledEntry)."""
    if getattr(_trace_state, "active", False):
        getattr(_trace_state, "cleared_uids", set()).add(uid)


def _is_tensor(x):
    return isinstance(x, Tensor)


class _StateSnapshot:
    """Save/restore all mutable fields of state tensors around a trace."""

    def __init__(self, tensors):
        self.tensors = tensors
        self.ids = {id(t) for t in tensors}
        self.saved = [(t._value, t._version, t._node, t.grad, t.stop_gradient)
                      for t in tensors]

    def restore(self):
        for t, (v, ver, node, grad, sg) in zip(self.tensors, self.saved):
            t._value = v
            t._version = ver
            t._node = node
            t.grad = grad
            t.stop_gradient = sg
        # State tensors CREATED during the trace (lazy optimizer accumulators,
        # the RNG key) may hold leaked tracers; re-init them from their spec.
        for t in fstate.state_tensors():
            if id(t) not in self.ids and isinstance(t._value, jax.core.Tracer):
                reinit = t.__dict__.get("_reinit")
                if reinit is None:
                    raise RuntimeError(
                        f"state tensor {t.name} created inside a to_static "
                        "trace without a _reinit spec")
                # escape the ambient trace so the rebuilt value is concrete
                with jax.ensure_compile_time_eval():
                    t._value = reinit()
                t._node = None
                t.grad = None


def _ordered_state():
    ts = fstate.state_tensors()
    ts.sort(key=lambda t: t.__dict__.get("_state_serial", 0))
    return ts


class StaticFunction:
    """Callable wrapper produced by @to_static."""

    def __init__(self, function, input_spec=None, build_strategy=None,
                 backend=None, donate_state=True, check=False, audit=False,
                 amp_policy=None, remat=None, guard=False):
        self._raw_function = function
        # guard=True arms the training-sentinel loss probe
        # (resilience/sentinel.py): every scalar float output leaf
        # (the loss) gets its value + finite flag computed INSIDE the
        # compiled program and returned as one tiny extra output — so
        # detection adds zero lifetime compiles (same trace, same
        # cache key) and the host reads a (n, 2) f32 array it was
        # going to sync anyway.  The parsed probe lands on
        # ``fn.last_guard`` and feeds the ambient TrainingSentinel.
        self._guard = bool(guard)
        self.last_guard = None
        # trace-scoped mixed-precision storage policy (amp/policy.py):
        # amp_policy="bf16" casts f32 activations to bf16 at Layer
        # boundaries (params stay f32 master weights) and enables the
        # O1 white-list downcasts; remat=True/"bf16" turns on the
        # model's recompute units ("bf16" also narrows saved boundary
        # activations).  Pushed around EVERY trace of this function —
        # eager code and other StaticFunctions never see it.
        self._amp_policy = amp_policy
        self._remat = remat
        # opt-in tracelint (analysis/): AST pass now, jaxpr pass at the
        # first compile of each signature — findings surface as
        # TracelintWarning instead of opaque trace-time errors
        self._check = bool(check)
        # opt-in shardlint (analysis/shard_rules + cost_audit): the full
        # SL-rule sharding/collective/memory audit of each signature's
        # traced jaxpr at first compile — findings surface as
        # ShardlintWarning; the latest CostReport lands on .last_audit
        self._audit = bool(audit)
        self.last_audit = None
        if self._check:
            from paddle_tpu import analysis
            analysis.warn_findings(analysis.lint_callable(function))
        # Dy2Static AST pass (jit/dy2static.py): tensor-dependent
        # if/while/for in the traced function (and, via convert_call, in
        # everything it calls) become select/lax.while_loop programs;
        # Python-valued control flow keeps eager semantics. Best-effort:
        # falls back to the untransformed function on any failure.
        from paddle_tpu.jit.dy2static import convert_to_static
        self._function = convert_to_static(function)
        self._input_spec = input_spec
        self._donate = donate_state
        self._compiled = {}
        self._last_state = None
        self.__name__ = getattr(function, "__name__", "static_fn")
        self._span_name = f"jit.{self.__name__}"
        self._param_names = None    # resolved lazily on first cache miss

    @property
    def dygraph_function(self):
        return self._raw_function

    def _make_pure(self, in_treedef, n_state, static_leaves):
        fn = self._function

        def pure(state_vals, tensor_vals):
            state_list = self._trace_state_list
            snap = _StateSnapshot(state_list)
            _trace_state.active = True
            _trace_state.cleared_uids = set()
            try:
                for t, v in zip(state_list, state_vals):
                    t._value = v
                    t._node = None
                    t.grad = None
                leaves = []
                ti = iter(tensor_vals)
                for s in static_leaves:
                    leaves.append(Tensor(next(ti)) if s is _ARRAY else s)
                args, kwargs = _tree.tree_unflatten(in_treedef, leaves)
                if self._amp_policy or self._remat:
                    from paddle_tpu.amp.policy import activation_residency
                    with activation_residency(
                            self._amp_policy if self._amp_policy
                            else None, remat=self._remat or False):
                        out = fn(*args, **kwargs)
                else:
                    out = fn(*args, **kwargs)
                from paddle_tpu.jit.dy2static import UNDEF as _UNDEF
                out_leaves, out_treedef = _tree.tree_flatten(out, is_leaf=_is_tensor)
                if any(o is _UNDEF for o in out_leaves):
                    raise ValueError(
                        "to_static: the function returned a variable "
                        "bound in only one branch of a tensor-valued "
                        "`if` (unrepresentable under a trace) — bind it "
                        "on every path")
                out_vals = [o._value if isinstance(o, Tensor) else o
                            for o in out_leaves]
                out_static = [_ARRAY if isinstance(o, (Tensor, jax.Array))
                              or hasattr(o, "aval") else o for o in out_leaves]
                new_state = [t._value for t in state_list]
                self._out_info = (out_treedef, out_static)
                # grads that survive to the end of the step (backward ran
                # and nothing cleared them) materialize back onto
                # param.grad — paddle semantics; a user reading .grad
                # after a jitted step must not silently see None
                grad_idx, grad_vals = [], []
                for i, t in enumerate(state_list):
                    g = t.grad
                    if g is not None and isinstance(
                            g._value, (jax.core.Tracer, jax.Array)):
                        grad_idx.append(i)
                        grad_vals.append(g._value)
                self._grad_idx = tuple(grad_idx)
                self._grad_cleared = frozenset(_trace_state.cleared_uids)
                arrays = [v for v, s in zip(out_vals, out_static) if s is _ARRAY]
                if not self._guard:
                    return arrays, new_state, grad_vals
                # sentinel probe: (value, isfinite) per scalar float
                # output leaf, f32, computed in-trace — NL-clean (one
                # scalar convert, no narrow reductions)
                probes = []
                for v, s in zip(out_vals, out_static):
                    if s is not _ARRAY:
                        continue
                    shp = jnp.shape(v)
                    if any(int(d) != 1 for d in shp):
                        continue
                    dt = getattr(v, "dtype", None)
                    if dt is None or not jnp.issubdtype(dt, jnp.floating):
                        continue
                    val = jnp.reshape(v, ()).astype(jnp.float32)
                    probes.append(jnp.stack(
                        [val, jnp.isfinite(val).astype(jnp.float32)]))
                guard_arr = (jnp.stack(probes) if probes
                             else jnp.zeros((0, 2), jnp.float32))
                return arrays, new_state, grad_vals, [guard_arr]
            finally:
                _trace_state.active = False
                snap.restore()
        return pure

    @staticmethod
    def _flatten_inputs(args, kwargs):
        """One flatten rule for every path that traces this function
        (__call__ and traced_program): tensor-like leaves become traced
        array inputs, everything else is a static (cache-keying) leaf."""
        leaves, in_treedef = _tree.tree_flatten((args, kwargs),
                                                is_leaf=_is_tensor)
        tensor_vals, static_leaves = [], []
        for l in leaves:
            if isinstance(l, Tensor):
                tensor_vals.append(l._value)
                static_leaves.append(_ARRAY)
            elif isinstance(l, jax.Array):
                tensor_vals.append(l)
                static_leaves.append(_ARRAY)
            else:
                static_leaves.append(l)
        return in_treedef, tensor_vals, static_leaves

    def _leaf_names(self, args, kwargs):
        """One human-readable name per flattened leaf of (args, kwargs),
        aligned with :meth:`_flatten_inputs` leaf order — so a recompile
        event can say WHICH argument's shape/dtype/static value changed
        (``ids``, ``arg1['mask']``, ...) instead of a leaf index."""
        if self._param_names is None:
            try:
                self._param_names = [
                    p.name for p in inspect.signature(
                        self._raw_function).parameters.values()
                    if p.kind in (p.POSITIONAL_ONLY,
                                  p.POSITIONAL_OR_KEYWORD)]
            except (TypeError, ValueError):
                self._param_names = []
        try:
            flat, _ = _tree.tree_flatten_with_path((args, kwargs),
                                                   is_leaf=_is_tensor)
        except Exception:  # noqa: BLE001 — naming is best-effort
            return None
        names = []
        for path, _leaf in flat:
            if len(path) >= 2 and getattr(path[0], "idx", None) == 0:
                i = getattr(path[1], "idx", None)
                base = (self._param_names[i]
                        if i is not None and i < len(self._param_names)
                        else f"arg{i}")
            elif len(path) >= 2:
                base = str(getattr(path[1], "key", path[1]))
            else:
                base = "args"
            names.append(base + "".join(str(p) for p in path[2:]))
        return names

    def __call__(self, *args, **kwargs):
        with _span(self._span_name):
            return self._call(args, kwargs)

    def _call(self, args, kwargs):
        in_treedef, tensor_vals, static_leaves = self._flatten_inputs(
            args, kwargs)

        for attempt in range(3):
            state_list = _ordered_state()
            state_vals = [t._value for t in state_list]
            if self._donate:
                # two state tensors can end up holding the SAME jax.Array
                # (e.g. set_state_dict from another live Layer's
                # state_dict) — donating one buffer twice is an XLA
                # execute error, so break accidental aliasing here
                seen = set()
                for i, v in enumerate(state_vals):
                    if id(v) in seen:
                        state_vals[i] = jnp.array(v, copy=True)
                        state_list[i]._value = state_vals[i]
                    else:
                        seen.add(id(v))
            reg_ver = fstate.registry_version()
            key = (
                in_treedef,
                tuple((tuple(v.shape), str(v.dtype)) for v in tensor_vals),
                tuple(s if s is _ARRAY else _hashable(s) for s in static_leaves),
                reg_ver,
            )
            entry = self._compiled.get(key)
            event = None
            if entry is None:
                prior_keys = list(self._compiled)
                t_trace0 = time.perf_counter()
                self._trace_state_list = state_list
                pure = self._make_pure(in_treedef, len(state_vals), static_leaves)
                jitted = jax.jit(pure, donate_argnums=(0,) if self._donate else ())
                # Discovery trace (no execution, nothing donated): lazily
                # created state (optimizer accumulators, RNG key) registers
                # during the trace; if that happened, retrace with it lifted.
                if self._check or self._audit:
                    # trace() exposes the jaxpr for the post-trace lint
                    # (TL4xx) / shardlint audit at no extra cost vs the
                    # discovery lower()
                    traced = jitted.trace(state_vals, tensor_vals)
                    from paddle_tpu import analysis
                    where = f"<to_static {self.__name__}>"
                    infos = _audit_input_infos(state_list, tensor_vals)
                    if self._check:
                        analysis.warn_findings(
                            analysis.check_jaxpr(traced.jaxpr, where=where))
                        # numlint rides the same opt-in: the numerics &
                        # precision-flow pass over the same traced
                        # program (NLxxx), warned alongside the TL4xx
                        # jaxpr findings
                        analysis.warn_findings(
                            analysis.check_numerics(traced.jaxpr,
                                                    where=where,
                                                    inputs=infos),
                            category=analysis.NumlintWarning,
                            prefix="numlint")
                        # kernlint: the KL pass over every pallas_call
                        # interior the program reaches (numlint keeps
                        # the body opaque; KL103 owns it)
                        analysis.warn_findings(
                            analysis.check_kernels(traced.jaxpr,
                                                   where=where),
                            category=analysis.KernlintWarning,
                            prefix="kernlint")
                    if self._audit:
                        findings, self.last_audit = analysis.audit_jaxpr(
                            traced.jaxpr, where=where, inputs=infos)
                        analysis.warn_findings(
                            findings, category=analysis.ShardlintWarning,
                            prefix="shardlint")
                else:
                    jitted.lower(state_vals, tensor_vals)
                if fstate.registry_version() != reg_ver:
                    continue
                self._compiled[key] = _CompiledEntry(
                    jitted, self._out_info, state_list, self._grad_idx,
                    self._grad_cleared)
                entry = self._compiled[key]
                # recompile attribution: diff this cache key against the
                # nearest cached signature so the event can say WHY the
                # miss happened (which arg's shape/dtype/static leaf, or
                # the state registry, changed)
                event = _obs_recompile.note_jit_compile(
                    self.__name__, key, prior_keys,
                    self._leaf_names(args, kwargs), _ARRAY,
                    trace_ms=round(
                        (time.perf_counter() - t_trace0) * 1e3, 3))
            jitted = entry.jitted
            t_run0 = time.perf_counter()
            if self._guard:
                (out_arrays, new_state, grad_vals,
                 guard_out) = jitted(state_vals, tensor_vals)
            else:
                out_arrays, new_state, grad_vals = jitted(state_vals,
                                                          tensor_vals)
                guard_out = None
            if event is not None:
                # first execution of a fresh entry: XLA compiles here
                # (the lower() above only traced), so this wall time is
                # compile-dominated
                event.compile_ms = round(
                    (time.perf_counter() - t_run0) * 1e3, 3)
            self._apply(entry, out_arrays, new_state, grad_vals)
            if guard_out is not None:
                self._note_guard(guard_out)
            return self._rewrap(entry, out_arrays)
        raise RuntimeError("to_static: state registry kept changing during trace")

    def _note_guard(self, guard_out):
        """Parse the in-trace probe outputs onto ``last_guard`` and
        hand them to the ambient TrainingSentinel (informational —
        the policy runs through explicit ``observe()`` calls)."""
        import numpy as np
        ga = np.asarray(guard_out[0], np.float64)
        values = [float(x) for x in ga[:, 0]] if ga.size else []
        finite = [bool(x >= 0.5) for x in ga[:, 1]] if ga.size else []
        self.last_guard = {
            "values": values,
            "finite": finite,
            "loss": values[0] if values else None,
            "loss_finite": finite[0] if finite else True,
        }
        try:
            from paddle_tpu.resilience import sentinel as _sentinel
            s = _sentinel.current()
            if s is not None:
                s.note_probe(self.__name__, self.last_guard)
        except Exception:
            pass

    def _apply(self, entry, out_arrays, new_state, grad_vals):
        state_list, grad_idx = entry.state_list, entry.grad_idx
        for t, v in zip(state_list, new_state):
            t._value = v
            t._version += 1
            t._node = None
        for i, gv in zip(grad_idx, grad_vals):
            t = state_list[i]
            if t.grad is None:
                t.grad = Tensor(gv, stop_gradient=True,
                                name=t.name + "@GRAD")
            elif t._uid in entry.grad_cleared:
                # the step clears before backward — fresh grads replace
                t.grad._value = gv
            else:
                # the step did NOT clear: eager semantics accumulate the
                # per-step grad onto the pre-call .grad (the compiled
                # program always starts its grads at None, so gv is this
                # step's delta, never a running total)
                t.grad._value = t.grad._value + gv

    def _rewrap(self, entry, out_arrays):
        out_treedef, out_static = entry.out_info
        it = iter(out_arrays)
        leaves = [Tensor(next(it)) if s is _ARRAY else s for s in out_static]
        return _tree.tree_unflatten(out_treedef, leaves)

    def traced_program(self, *args, **kwargs):
        """Trace (never compile or run) this signature; returns
        ``(closed_jaxpr, input_infos)`` where `input_infos` is one
        :class:`analysis.InputInfo` per jaxpr invar — the lifted state
        tensors (with their names, kinds and dist_spec shardings) then
        the user tensor args.  This is the entry point shardlint's CLI
        and bench lane use to audit a program without paying a compile.
        """
        in_treedef, tensor_vals, static_leaves = self._flatten_inputs(
            args, kwargs)
        # same discovery-retrace loop as __call__ (lazily created state
        # registers during the first trace), minus donation/compilation
        for attempt in range(3):
            state_list = _ordered_state()
            state_vals = [t._value for t in state_list]
            reg_ver = fstate.registry_version()
            self._trace_state_list = state_list
            pure = self._make_pure(in_treedef, len(state_vals),
                                   static_leaves)
            traced = jax.jit(pure).trace(state_vals, tensor_vals)
            if fstate.registry_version() != reg_ver:
                # lazily created state (optimizer accumulators, the RNG
                # key) registered during the trace: retrace with it
                # lifted so the audit sees it as a named input
                continue
            return traced.jaxpr, _audit_input_infos(state_list, tensor_vals)
        raise RuntimeError(
            "to_static: state registry kept changing during trace")

    def concrete_program(self, *args, **kwargs):
        raise NotImplementedError


class _Array:
    __slots__ = ()

    def __repr__(self):
        return "<array-leaf>"


_ARRAY = _Array()


def _hashable(x):
    try:
        hash(x)
        return x
    except TypeError:
        return repr(x)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, check=False, audit=False, amp_policy=None,
              remat=None, guard=False, **kwargs):
    """Decorator/wrapper: compile a dygraph function or Layer to one XLA program.

    Usage matches paddle.jit.to_static: bare decorator, decorator with
    input_spec, or `net = to_static(net)` on a Layer.

    ``check=True`` opts into tracelint (paddle_tpu.analysis): an AST
    pass over the function and its module-local reach at wrap time, and
    a jaxpr pass after each first-compile — hazards are reported as
    ``TracelintWarning`` with TLxxx codes and file:line.  The numlint
    numerics & precision-flow pass (NLxxx — narrow accumulation,
    double-rounding, unstabilized narrow transcendentals, quantization
    readiness) runs on the same trace and warns as
    ``NumlintWarning``.

    ``audit=True`` opts into shardlint: the SL-rule sharding /
    collective-safety / memory-layout audit of each signature's traced
    jaxpr at first compile.  Findings surface as ``ShardlintWarning``
    and the latest :class:`analysis.CostReport` (estimated peak HBM,
    MXU padding waste) is kept on ``fn.last_audit``.

    ``amp_policy="bf16"`` enables bf16 activation residency for the
    traced step (params stay f32 master weights); ``remat=True`` /
    ``remat="bf16"`` turns on the model's recompute units, the latter
    saving boundary activations in bf16.  Both are trace-scoped — see
    paddle_tpu/amp/policy.py and docs/performance_guide.md.

    ``guard=True`` arms the training-sentinel loss probe: each scalar
    float output's value + finite flag is computed inside the compiled
    program (zero extra compiles — the probe is part of the one traced
    program) and parsed onto ``fn.last_guard``.  Pair with
    ``Optimizer(guard=True)`` for the gradient-side probe and the
    in-trace zero-update skip — docs/resilience.md "Numerics
    sentinel".
    """
    from paddle_tpu.nn.layer.layers import Layer

    def wrap(fn):
        if isinstance(fn, Layer):
            static = StaticFunction(fn.forward, input_spec, check=check,
                                    audit=audit, amp_policy=amp_policy,
                                    remat=remat, guard=guard)
            fn.forward = static
            fn._static_forward = static
            return fn
        return StaticFunction(fn, input_spec, check=check, audit=audit,
                              amp_policy=amp_policy, remat=remat,
                              guard=guard)

    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(function):
    function._not_to_static = True
    return function


class ProgramTranslator:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enable_to_static = True
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, enable_to_static):
        self.enable_to_static = enable_to_static


def enable_to_static(flag=True):
    ProgramTranslator().enable(flag)
