"""Admission scheduling: FCFS queue, page-budget policy, and the
prompt-length bucketing that bounds XLA recompiles.

Bucketing contract (the TL3xx recompile-storm hazard, made a feature):
every prefill runs at one of ``len(buckets)`` padded shapes, decode runs
at exactly one shape, and sampling adds two (prefill-width and
decode-width).  The engine therefore compiles AT MOST
``len(buckets) + 3`` programs over its whole lifetime — countable,
declared up front (`compile_bound`), and asserted in CI.

Admission is strict FCFS with head-of-line blocking: if the oldest
waiting request does not fit (no free slot, or the page budget can't
cover its bucketed prompt plus one growth page), nothing behind it is
admitted either.  Skipping ahead would starve long prompts forever on a
busy pool; head-of-line blocking keeps latency ordering predictable.

Preemption is deterministic: when decode needs a page and the pool is
dry, the LATEST-arrived running request is evicted (LIFO victim — the
request that has consumed the least scheduler goodwill), its pages are
freed, and it re-enters the waiting queue at the front.
"""
from __future__ import annotations

from collections import deque

from paddle_tpu.serving.request import RequestState

__all__ = ["AdmissionRejected", "bucket_for", "default_buckets",
           "Scheduler"]


class AdmissionRejected(RuntimeError):
    """Explicit backpressure: the engine refuses NEW work (bounded
    admission queue full, or the health state machine is DRAINING)
    instead of queueing unboundedly.  Callers retry elsewhere / later —
    `reason` is machine-readable ("queue_full" | "draining")."""

    def __init__(self, reason, detail=""):
        self.reason = reason
        super().__init__(f"admission rejected ({reason})"
                         + (f": {detail}" if detail else ""))


def default_buckets(max_model_len, smallest=16):
    """Powers-of-two padded prompt lengths up to max_model_len."""
    buckets = []
    b = smallest
    while b < max_model_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_model_len)
    return tuple(buckets)


def bucket_for(length, buckets):
    """Smallest bucket >= length; raises when the prompt can't fit."""
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(
        f"prompt length {length} exceeds the largest bucket "
        f"{buckets[-1]} — raise max_model_len / add a bucket")


class Scheduler:
    """FCFS admission with a page-budget gate.

    The scheduler owns the WAITING queue only; running-state ownership
    (slots, allocator) stays with the engine, which passes the relevant
    views in.  Keeping the policy pure over those views makes it
    unit-testable without compiling anything.
    """

    def __init__(self, buckets, page_size, growth_reserve_pages=1,
                 max_queue_depth=None):
        self.buckets = tuple(sorted(buckets))
        self.page_size = int(page_size)
        # pages kept back per admission so one decode step can always
        # grow the newly admitted sequence without instant preemption
        self.growth_reserve_pages = int(growth_reserve_pages)
        # bounded admission: NEW enqueues past this depth raise
        # AdmissionRejected (None = unbounded, the historical behavior)
        self.max_queue_depth = (int(max_queue_depth)
                                if max_queue_depth is not None else None)
        self._waiting = deque()

    # ---- queue ----
    def enqueue(self, request):
        if self.max_queue_depth is not None and \
                len(self._waiting) >= self.max_queue_depth:
            raise AdmissionRejected(
                "queue_full",
                f"waiting queue at max_queue_depth={self.max_queue_depth}")
        self._waiting.append(request)

    def requeue_front(self, request):
        """Evicted requests keep their FCFS priority.  Exempt from the
        queue bound: the request was already admitted once, and
        dropping it here would turn a preemption into a data loss."""
        self._waiting.appendleft(request)

    def withdraw(self, request):
        """Remove a still-WAITING request from the queue (generate()
        unwinding a partially-enqueued batch under backpressure).
        Missing is fine — the request may already have been rejected."""
        try:
            self._waiting.remove(request)
        except ValueError:
            pass

    def drain_waiting(self):
        """Remove and return EVERY waiting request in queue order (the
        router's drain hook: still-queued work migrates to another
        replica instead of waiting out this one's retirement)."""
        out = list(self._waiting)
        self._waiting.clear()
        return out

    def pop_expired(self, now):
        """Remove and return every waiting request whose deadline has
        passed (deterministic: queue order preserved for survivors)."""
        expired = [r for r in self._waiting if r.past_deadline(now)]
        if expired:
            self._waiting = deque(r for r in self._waiting
                                  if not r.past_deadline(now))
        return expired

    @property
    def queue_depth(self):
        return len(self._waiting)

    def has_waiting(self):
        return bool(self._waiting)

    def peek(self):
        return self._waiting[0] if self._waiting else None

    # ---- policy ----
    def pages_for_prompt(self, prompt_len):
        """Pages an admission must secure: the FULL bucketed shape is
        never written (padding is routed to the garbage page), so only
        the real prompt length counts, plus the growth reserve."""
        return (-(-prompt_len // self.page_size)
                + self.growth_reserve_pages)

    def admissible(self, request, free_slots, free_pages):
        """Can `request` be admitted right now?"""
        if free_slots <= 0:
            return False
        need = self.pages_for_prompt(len(request.replay_token_ids))
        return need <= free_pages

    def pop_admissible(self, free_slots, free_pages):
        """Pop the queue head if it fits (strict FCFS: a non-fitting
        head blocks everything behind it). Returns None when nothing is
        admissible."""
        if not self._waiting:
            return None
        head = self._waiting[0]
        if not self.admissible(head, free_slots, free_pages):
            return None
        return self._waiting.popleft()

    def select_victim(self, running):
        """Deterministic preemption: evict the latest-arrived DECODE
        request. Returns None when there is nothing to evict."""
        candidates = [r for r in running
                      if r.state == RequestState.DECODE]
        if not candidates:
            return None
        return max(candidates, key=lambda r: r.arrival_index)

    def bucket_for_len(self, length):
        return bucket_for(length, self.buckets)
