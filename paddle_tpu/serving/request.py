"""Request/sequence state machine for the serving engine.

Lifecycle (docs/serving.md has the full diagram)::

    WAITING --admit--> PREFILL --first token--> DECODE --stop--> FINISHED
       ^                                          |
       '--------------- EVICTED <--preempted------'

EVICTED requests re-enter at the FRONT of the waiting queue (they were
admitted once, so FCFS priority says they go first) and are replayed by
prefilling ``prompt + tokens generated so far`` — sampling seeds fold in
the absolute token position, so a replayed request regenerates the exact
same continuation it would have produced uninterrupted.
"""
from __future__ import annotations

import enum


class RequestState(enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    DECODE = "decode"
    FINISHED = "finished"
    EVICTED = "evicted"


# legal transitions; anything else is an engine bug.  WAITING/EVICTED
# may go straight to FINISHED: deadline expiry finishes a queued
# request without it ever (re-)reaching a slot.
_TRANSITIONS = {
    RequestState.WAITING: {RequestState.PREFILL, RequestState.FINISHED},
    RequestState.PREFILL: {RequestState.DECODE, RequestState.FINISHED},
    RequestState.DECODE: {RequestState.FINISHED, RequestState.EVICTED},
    RequestState.EVICTED: {RequestState.PREFILL, RequestState.FINISHED},
    RequestState.FINISHED: set(),
}


class SamplingParams:
    """Per-request sampling configuration.

    temperature == 0 means greedy (argmax); top_k <= 0 and top_p >= 1
    disable those filters. `seed` + the absolute token position fully
    determine each draw, so generation is batch-composition independent
    (continuous batching, sequential decode, and preemption replay all
    produce identical tokens).

    `deadline_s` is a per-request TTL measured from arrival: a request
    still queued (or still decoding) past its deadline is finished with
    ``finish_reason="deadline"`` at the next step boundary — enforced
    deadline semantics rather than unbounded queueing.
    """

    def __init__(self, max_new_tokens=16, temperature=0.0, top_k=0,
                 top_p=1.0, seed=0, eos_token_id=None, deadline_s=None):
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if not 0.0 < top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive (or None)")
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        self.eos_token_id = eos_token_id
        self.deadline_s = float(deadline_s) if deadline_s is not None \
            else None

    def __repr__(self):
        return (f"SamplingParams(max_new_tokens={self.max_new_tokens}, "
                f"temperature={self.temperature}, top_k={self.top_k}, "
                f"top_p={self.top_p}, seed={self.seed}, "
                f"eos_token_id={self.eos_token_id}, "
                f"deadline_s={self.deadline_s})")


class Request:
    """One generation request moving through the engine.

    `stream` is an optional ``callback(request, token_id, finished)``
    invoked once per NEW token (replayed tokens after an eviction are
    not re-streamed).
    """

    def __init__(self, request_id, prompt_token_ids, sampling_params,
                 arrival_index, stream=None):
        if not prompt_token_ids:
            raise ValueError("prompt must contain at least one token")
        self.request_id = request_id
        self.prompt_token_ids = list(prompt_token_ids)
        self.sampling_params = sampling_params
        self.arrival_index = int(arrival_index)  # FCFS / victim ordering
        self.stream = stream
        self.state = RequestState.WAITING
        self.output_token_ids = []
        self._streamed = 0          # tokens already delivered to `stream`
        self._stream_done = False   # final last=True signal sent
        self.slot = None            # decode batch slot while running
        self.num_evictions = 0
        self.finish_reason = None   # "stop" | "length" | "deadline"
        # metrics timestamps (host clocks; filled by the engine)
        self.deadline_t = None      # arrive_t + deadline_s, or None
        self.arrive_t = None
        self.first_token_t = None
        self.finish_t = None
        self.last_token_t = None
        # distributed-trace identity (observability.TraceContext or
        # None) — set at admission, carried across adoption/handoff
        self.trace = None
        # set when adopted/imported onto this engine; cleared when the
        # first resumed token observes the ttft_decode stage histogram
        self._resume_t = None

    # ---- state machine ----
    def transition(self, new_state):
        if new_state not in _TRANSITIONS[self.state]:
            raise RuntimeError(
                f"illegal request transition {self.state.value} -> "
                f"{new_state.value} (request {self.request_id})")
        self.state = new_state

    # ---- derived views ----
    @property
    def replay_token_ids(self):
        """What a (re-)prefill must feed the model: the prompt plus any
        tokens already generated before an eviction."""
        return self.prompt_token_ids + self.output_token_ids

    @property
    def total_len(self):
        return len(self.prompt_token_ids) + len(self.output_token_ids)

    @property
    def is_finished(self):
        return self.state == RequestState.FINISHED

    def append_token(self, token_id, now=None):
        """Record a newly sampled token; returns True if it was NEW
        (not a replay duplicate — replays never reach here because the
        engine re-prefills rather than re-samples)."""
        self.output_token_ids.append(int(token_id))
        if self.first_token_t is None and now is not None:
            self.first_token_t = now
        self.last_token_t = now
        return True

    def deliver(self, finished):
        """Stream not-yet-delivered tokens to the callback.  A finish
        with nothing left to stream (deadline expiry of a queued
        request, tokens already drained) still fires one final
        ``(request, None, True)`` completion signal — a stream consumer
        must never wait forever for its ``last=True``."""
        if self.stream is None:
            self._streamed = len(self.output_token_ids)
            return
        toks = self.output_token_ids
        while self._streamed < len(toks):
            t = toks[self._streamed]
            self._streamed += 1
            last = finished and self._streamed == len(toks)
            if last:
                self._stream_done = True
            self.stream(self, t, last)
        if finished and not self._stream_done:
            self._stream_done = True
            self.stream(self, None, True)

    def past_deadline(self, now):
        return self.deadline_t is not None and now >= self.deadline_t

    def should_stop(self):
        """Returns the finish reason if the request is done, else None."""
        sp = self.sampling_params
        if (sp.eos_token_id is not None and self.output_token_ids
                and self.output_token_ids[-1] == sp.eos_token_id):
            return "stop"
        if len(self.output_token_ids) >= sp.max_new_tokens:
            return "length"
        return None

    def __repr__(self):
        return (f"Request({self.request_id}, state={self.state.value}, "
                f"prompt={len(self.prompt_token_ids)}t, "
                f"out={len(self.output_token_ids)}t, slot={self.slot})")


class GenerationResult:
    """What `LLMEngine.generate` returns per prompt."""

    def __init__(self, request):
        self.request_id = request.request_id
        self.prompt_token_ids = list(request.prompt_token_ids)
        self.output_token_ids = list(request.output_token_ids)
        self.finish_reason = request.finish_reason
        self.num_evictions = request.num_evictions

    def __repr__(self):
        return (f"GenerationResult({self.request_id}, "
                f"{len(self.output_token_ids)} tokens, "
                f"finish={self.finish_reason})")
