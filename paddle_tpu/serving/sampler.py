"""Traced token sampling: greedy / temperature / top-k / top-p.

One pure function over jnp arrays, vmapped across the batch, jitted by
the engine at exactly two shapes (prefill width 1, decode width B) — it
never recompiles per request because every knob (temperature, top_k,
top_p, seed) is a TRACED operand, not a static argument.

Determinism contract: the key for a draw is
``fold_in(fold_in(PRNGKey(seed), position))`` where `position` is the
ABSOLUTE index of the token being sampled.  Batch composition, slot
assignment, and eviction/replay history cannot change a request's
tokens.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample_tokens"]

_NEG_INF = jnp.finfo(jnp.float32).min


def _sample_row(logits, seed, position, temperature, top_k, top_p):
    """One row: logits [V] f32 -> token id (int32)."""
    V = logits.shape[0]
    logits = logits.astype(jnp.float32)

    # temperature; <=0 means greedy (selected at the end)
    scaled = logits / jnp.maximum(temperature, 1e-6)

    # top-k: mask everything below the k-th largest logit (k<=0: off)
    k = jnp.where(top_k <= 0, V, jnp.clip(top_k, 1, V))
    desc = jnp.sort(scaled)[::-1]
    kth = desc[jnp.maximum(k - 1, 0)]
    scaled = jnp.where(scaled < kth, _NEG_INF, scaled)

    # top-p (nucleus) over the top-k-filtered distribution: keep the
    # smallest prefix of descending-prob tokens whose mass reaches p
    probs = jax.nn.softmax(scaled)
    sp = jnp.sort(probs)[::-1]
    cum = jnp.cumsum(sp)
    keep_sorted = (cum - sp) < top_p        # mass BEFORE this token < p
    keep_sorted = keep_sorted.at[0].set(True)  # never drop the argmax
    pmin = jnp.min(jnp.where(keep_sorted, sp, jnp.inf))
    log_probs = jnp.where(probs >= pmin, jnp.log(probs), _NEG_INF)

    # Gumbel-max draw from the filtered distribution
    key = jax.random.fold_in(jax.random.PRNGKey(seed), position)
    gumbel = jax.random.gumbel(key, (V,), jnp.float32)
    sampled = jnp.argmax(log_probs + gumbel)

    greedy = jnp.argmax(logits)
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def sample_tokens(logits, seeds, positions, temperatures, top_ks, top_ps):
    """Batched sampling (pure, trace-safe).

    logits [B, V] f32; seeds/positions/top_ks [B] int32;
    temperatures/top_ps [B] f32 -> token ids [B] int32.
    """
    return jax.vmap(_sample_row)(
        logits, seeds.astype(jnp.int32), positions.astype(jnp.int32),
        temperatures.astype(jnp.float32), top_ks.astype(jnp.int32),
        top_ps.astype(jnp.float32))
