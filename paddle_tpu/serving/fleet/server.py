"""ReplicaServer — the worker-process side of the serving fleet.

One per replica rank: owns (after the controller's ``boot`` verb) a
real :class:`~paddle_tpu.serving.engine.LLMEngine`, and serves the
:mod:`.wire` RPC lane in a single-threaded loop — every engine call
runs on this one thread, so the engine needs no extra locking and the
whole process inherits the engine's determinism.

The engine is built with NO stream callbacks: streamed-token delivery
is the CONTROLLER's job (exactly-once from the seq-numbered step
responses, see :mod:`.handle`); the server only reports events and
drains ``finished_requests`` into each step response so the
authoritative token history and finish reason cross the wire with the
step that produced them.

Heartbeats: the worker entrypoint installs a
:class:`~paddle_tpu.resilience.fleet.HeartbeatPublisher` with
``payload_fn=server.telemetry`` — every beat carries queue depth,
page occupancy and health state, and a SIGSTOP freezes the publisher
thread together with the serve loop, which is precisely what turns a
wedged replica into a watchdog DEAD verdict.  In-process tests pass
``inline_beats=True`` instead and the loop itself beats between RPCs
(a parked loop then goes silent, same verdict path, no threads).

Chaos hook ``serving.fleet.step`` fires before every engine step:
``rank_kill`` (SIGKILL — the crash path) and ``wedge`` (SIGSTOP /
park — the timeout path) are the two faults of the acceptance proof.
"""
from __future__ import annotations

import threading
import time

from paddle_tpu.observability import span, use_context
from paddle_tpu.resilience import fleet as _fleet
from paddle_tpu.resilience.faultinject import fire as _fire
from paddle_tpu.serving.fleet import wire

__all__ = ["ReplicaServer"]


class ReplicaServer:
    def __init__(self, client, rank, engine_factory, *, config=None,
                 namespace_fn=None, publisher=None, inline_beats=False):
        self._client = client
        self.rank = int(rank)
        self._factory = engine_factory
        self._config = config or _fleet.get_config()
        self._ns = namespace_fn or _fleet.coord_namespace
        self._publisher = publisher
        self._inline_beats = bool(inline_beats)
        self._lock = threading.Lock()   # guards the engine REFERENCE
        self._engine = None             # (calls run on the loop thread)
        self._stop = threading.Event()
        self.steps = 0
        self.requests_served = 0

    @property
    def engine(self):
        with self._lock:
            return self._engine

    def stop(self):
        self._stop.set()

    # ------------------------------------------------------ telemetry
    def telemetry(self):
        """Heartbeat payload (and step-response rider): the live
        admission signals the router's scoring reads.  Runs on the
        publisher thread — every read is a GIL-atomic int/len read of
        engine state, and a mid-mutation glimpse only skews one beat's
        routing score, never correctness."""
        e = self.engine
        if e is None:
            return {"health": 0, "queue_depth": 0,
                    "page_occupancy": 0.0, "num_running": 0,
                    "booted": False}
        return {"health": int(e.health.state),
                "queue_depth": int(e.queue_depth),
                "page_occupancy": round(float(e.page_occupancy), 4),
                "num_running": int(e.num_running),
                "booted": True}

    # ------------------------------------------------------ serve loop
    def serve(self):
        """Blocking request loop; returns after a ``shutdown`` verb or
        :meth:`stop`.  Lane seq starts at 0 and the controller owns
        it, so a request is never skipped or double-served."""
        seq = 0
        recv_s = max(0.25, self._config.kv_slice_s * 2.0)
        last_beat = 0.0
        while not self._stop.is_set():
            if self._inline_beats and self._publisher is not None:
                now = time.monotonic()
                if now - last_beat >= self._publisher._interval:
                    self._publisher.publish_once()
                    last_beat = now
            try:
                method, payload, ctx = wire.read_request(
                    self._client, self._ns(), self.rank, seq, recv_s,
                    config=self._config)
            except _fleet.CollectiveTimeout:
                continue            # empty slice window: poll stop/beat
            try:
                # the envelope's trace context (if any) becomes ambient
                # for the verb, so engine spans on THIS process record
                # under the originating request's trace
                with use_context(ctx):
                    result = self._dispatch(method, payload or {})
            except Exception as e:
                wire.post_response(self._client, self._ns(), self.rank,
                                   seq, error=e)
            else:
                wire.post_response(self._client, self._ns(), self.rank,
                                   seq, result=result)
            self.requests_served += 1
            seq += 1
            if method == "shutdown":
                break

    # ------------------------------------------------------- handlers
    def _dispatch(self, method, p):
        if method == "ping":
            return {"rank": self.rank}
        if method == "boot":
            with span("serving.fleet.boot", rank=self.rank):
                engine = self._factory(p)
            with self._lock:
                self._engine = engine
            return {"ok": True}
        if method == "shutdown":
            self._stop.set()
            e = self.engine
            if e is not None:
                e.shutdown()
            return {"ok": True}
        engine = self.engine
        if engine is None:
            raise RuntimeError(
                f"replica rank {self.rank} has no engine yet — the "
                f"controller must send 'boot' first")
        if method == "warmup":
            return engine.warmup()
        if method == "add":
            return engine.add_request(p["prompt"],
                                      wire.sp_from_dict(p.get("sp")))
        if method == "adopt":
            age_s = p.get("age_s")
            arrive_t = (None if age_s is None
                        else engine.metrics.clock() - float(age_s))
            return engine.adopt_request(
                p["prompt"], wire.sp_from_dict(p.get("sp")),
                generated_token_ids=p.get("generated", ()),
                streamed=p.get("streamed"), arrive_t=arrive_t,
                arrival_index=p.get("arrival_index"))
        if method == "step":
            # the chaos hook of the acceptance proof: rank_kill /
            # wedge land here, mid-decode from the fleet's view
            _fire("serving.fleet.step", rank=self.rank,
                  step=self.steps)
            evs = engine.step()
            self.steps += 1
            finished = []
            while engine.finished_requests:
                rid, req = engine.finished_requests.popitem(last=False)
                finished.append({
                    "rid": rid,
                    "tokens": [int(t) for t in req.output_token_ids],
                    "finish_reason": req.finish_reason})
            return {"events": [[rid, tok, bool(fin)]
                               for rid, tok, fin in evs],
                    "finished": finished,
                    "telemetry": self.telemetry()}
        if method == "release_waiting":
            return [{"rid": r.request_id,
                     "tokens": [int(t) for t in r.output_token_ids]}
                    for r in engine.release_waiting()]
        if method == "export_handoff":
            state = engine.export_page_state(
                p["request_id"], release=p.get("release", True))
            blob = wire.pack_state(state)
            key = wire.handoff_key(self._ns(), p["hid"])
            _fleet.kv_set_bytes(self._client, key, blob)
            return {"hid": p["hid"], "bytes": len(blob),
                    "pages": len(state["layers"][0][next(
                        iter(state["layers"][0]))])}
        if method == "import_handoff":
            key = wire.handoff_key(self._ns(), p["hid"])
            blob = _fleet.kv_get_bytes(
                self._client, key, self._config.collective_timeout_s,
                site="serving.fleet.handoff", config=self._config)
            state = wire.unpack_state(blob)
            rid = engine.import_page_state(state)
            # consume the blob only AFTER a successful import — a
            # rejected import (no slot/pages yet) must stay retryable
            try:
                self._client.key_value_delete(key)
            except Exception:
                pass
            return rid
        if method == "audit":
            m = engine.metrics
            return {"compiled": int(m.compile_count),
                    "bound": int(m.compile_bound),
                    "cache_loads": int(m.aot_cache_loads),
                    "steps": self.steps,
                    "generated_tokens": int(m.generated_tokens)}
        raise ValueError(f"unknown serving-fleet RPC method {method!r}")
