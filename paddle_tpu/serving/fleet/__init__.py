"""paddle_tpu.serving.fleet — elastic multi-host serving.

Runs :class:`~paddle_tpu.serving.engine.LLMEngine` replicas in
separate OS processes and fronts them with the STOCK
:mod:`paddle_tpu.serving.router` — the router never learns its
engines are remote.  The pieces (docs/serving.md "Multi-host fleet"):

- :mod:`.wire` — a tiny ordered RPC over the coordination-service KV
  store plus the npz page-handoff format;
- :class:`.RemoteEngineClient` — the controller-side process-replica
  handle with the engine surface the router drives (exactly-once
  stream delivery from seq-numbered step responses, watchdog-aborted
  waits, ``age_s`` deadline re-anchoring across migrations);
- :class:`.ReplicaServer` — the worker-process serve loop around a
  real engine, with heartbeat telemetry and the
  ``serving.fleet.step`` chaos hook;
- :class:`.ServingFleet` — the controller: router + fleet watchdog +
  respawn-elsewhere onto prespawned spare ranks, booting warm from
  the shared AOT program cache;
- :class:`.DisaggregatedEngine` — disaggregated prefill/decode over
  the quantized page handoff, token-identical to a monolithic run
  within the bounded-compile contract.

The multi-process entrypoint is :mod:`.worker` (spawned under
``paddle_tpu.distributed.launch`` by the chaos proof in
tests/test_distributed_multiprocess.py and the bench lane).
"""
from paddle_tpu.serving.fleet.controller import (FleetServingConfig,
                                                 ServingFleet)
from paddle_tpu.serving.fleet.disagg import (DisaggregatedEngine,
                                             DisaggResult)
from paddle_tpu.serving.fleet.handle import (FinishedRemote,
                                             RemoteEngineClient)
from paddle_tpu.serving.fleet.server import ReplicaServer
from paddle_tpu.serving.fleet.wire import (RemoteReplicaError,
                                           pack_state, unpack_state)

__all__ = [
    "DisaggResult",
    "DisaggregatedEngine",
    "FinishedRemote",
    "FleetServingConfig",
    "RemoteEngineClient",
    "RemoteReplicaError",
    "ReplicaServer",
    "ServingFleet",
    "pack_state",
    "unpack_state",
]
