"""RemoteEngineClient — a process-replica handle with the LLMEngine
surface the PR 11 Router drives.

The router never learns it is holding a remote engine: this proxy
exposes exactly the slice of the engine API the router uses —
``add_request`` / ``adopt_request`` / ``step`` / ``has_unfinished`` /
``release_waiting`` / ``finished_requests`` / ``warmup`` /
``shutdown`` plus the telemetry properties ``health`` /
``queue_depth`` / ``page_occupancy`` / ``num_running`` — and forwards
each over the :mod:`.wire` KV-RPC lane to a
:class:`~paddle_tpu.serving.fleet.server.ReplicaServer` in another OS
process.  The existing ACTIVE→DRAINING→DEAD lifecycle, spillover,
zero-data-loss failover and respawn machinery then work unchanged
across the process boundary.

Exactly-once streams: the replica engine runs with NO stream
callbacks — delivery happens only HERE, from the seq-numbered step
response (each response is consumed exactly once by wire
construction), so a token is either delivered from the one response
that carried it, or — if the replica died before responding — never
delivered and regenerated token-identically by the adoption replay on
the next replica.  The router's wrapper stream stays the single
exactly-once tap either way.

Failure surface: a replica that crashed (SIGKILL) or wedged (SIGSTOP)
misses its response; the watchdog's DEAD verdict aborts the pending
wait with a ``CollectiveTimeout`` which :meth:`step` lets fly — the
router catches ANY step exception and runs its normal failover, so a
watchdog verdict and an in-process engine crash take the identical
recovery path.  The last verdict is kept on ``last_timeout`` for the
chaos proof / bench lane.

Clock discipline (the deadline-TTL fix, ISSUE 16 satellite 2):
``adopt_request``'s `arrive_t` is the ROUTER's ``time.perf_counter``
reading — meaningless in another process — so the proxy ships
``age_s = now - arrive_t`` and the server re-anchors against the
replica engine's own clock.  A ``deadline_s`` TTL therefore keeps
counting from FIRST arrival, never restarting per migration.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict

from paddle_tpu.serving.fleet import wire

__all__ = ["RemoteEngineClient", "FinishedRemote"]


class _HealthShim:
    """`ReplicaHandle.telemetry()` reads ``int(engine.health.state)``;
    this carries the replica-reported health int across the wire."""

    def __init__(self, state=0):
        self.state = int(state)


class FinishedRemote:
    """Controller-side stand-in for a finished Request — the two
    fields the router's close-out path reads, mirrored from the step
    response's authoritative finished table."""

    __slots__ = ("request_id", "output_token_ids", "finish_reason")

    def __init__(self, request_id, output_token_ids, finish_reason):
        self.request_id = request_id
        self.output_token_ids = [int(t) for t in output_token_ids]
        self.finish_reason = finish_reason


class RemoteEngineClient:
    """One controller-side handle per replica worker process.

    Thread-safety: the router already serializes every engine call
    under its own RLock, but the proxy keeps its mirrors under a
    private lock anyway — telemetry refreshes may arrive from the
    fleet-monitor thread via :meth:`note_telemetry` while the router
    thread steps.  Stream callbacks fire OUTSIDE the proxy lock.
    """

    def __init__(self, client, rank, *, namespace_fn, config,
                 abort_if=None, clock=time.perf_counter,
                 metrics_name=None, hold_verdict=None,
                 release_verdict=None):
        self._client = client
        self.rank = int(rank)
        self._ns = namespace_fn
        self._config = config
        self._abort_if = abort_if
        # boot-phase verdict guards (the controller wires these to the
        # fleet monitor): warmup compiles/cache-loads silence the
        # replica's inline beats, and a spurious DEAD verdict there is
        # terminal — see FleetMonitor.hold_verdict
        self._hold_verdict = hold_verdict or (lambda for_s: None)
        self._release_verdict = release_verdict or (lambda: None)
        self._clock = clock
        self._metrics_name = metrics_name or f"serving.remote.r{rank}"
        self._lock = threading.Lock()
        self._seq = 0
        self._streams = {}          # erid -> stream callable
        self._unfinished = set()    # erid mirror
        self.finished_requests = OrderedDict()
        self._telemetry = {"health": 0, "queue_depth": 0,
                           "page_occupancy": 0.0, "num_running": 0}
        self.last_timeout = None    # CollectiveTimeout.to_dict()
        self.detect_s = None        # verdict latency of the LAST step
        self._dead = False

    # ------------------------------------------------------------ RPC
    def call(self, method, payload=None, timeout_s=None):
        """One ordered RPC round trip (public: the controller uses it
        for boot/handoff/audit verbs the router never sees)."""
        with self._lock:
            seq = self._seq
            self._seq += 1
        ns = self._ns()
        wire.post_request(self._client, ns, self.rank, seq, method,
                          payload)
        t0 = time.monotonic()
        try:
            return wire.await_response(
                self._client, ns, self.rank, seq,
                timeout_s if timeout_s is not None
                else self._config.collective_timeout_s,
                abort_if=self._abort_if, config=self._config)
        except Exception as e:
            to_dict = getattr(e, "to_dict", None)
            if to_dict is not None:
                with self._lock:
                    self.last_timeout = to_dict()
                    self.detect_s = time.monotonic() - t0
                    self._dead = True
            # reap the abandoned request (protolint PL102): the
            # controller is about to fail this stream over, but a
            # merely-wedged (SIGSTOP) replica that resumes would still
            # read the request and serve it a second time elsewhere —
            # delete-on-abandon keeps the lane exactly-once.  Best
            # effort: if the replica already consumed it, the delete
            # is a no-op; if the coordinator itself is gone, the
            # namespace reap is the backstop.
            try:
                self._client.key_value_delete(
                    wire.req_key(ns, self.rank, seq))
            except Exception:
                pass
            raise

    # -------------------------------------------- router engine surface
    def _call_admission(self, method, payload):
        """Admission verbs (add/adopt) against a replica that dies
        MID-CALL must read as a refusal — the router then spills to
        the next candidate (the request is still the caller's) instead
        of the whole admission path crashing on one dead target."""
        from paddle_tpu.serving.scheduler import AdmissionRejected
        try:
            return self.call(method, payload)
        except Exception as e:
            if getattr(e, "to_dict", None) is None:
                raise               # typed remote errors pass through
            raise AdmissionRejected(
                "replica_dead",
                f"rank {self.rank} unresponsive during {method} "
                f"({getattr(e, 'verdict', '?')})") from e

    def add_request(self, prompt_token_ids, sampling_params=None,
                    stream=None):
        erid = self._call_admission("add", {
            "prompt": [int(t) for t in prompt_token_ids],
            "sp": wire.sp_to_dict(sampling_params)})
        with self._lock:
            if stream is not None:
                self._streams[erid] = stream
            self._unfinished.add(erid)
            # optimistic bump: an admission BURST lands before the next
            # step/heartbeat telemetry does — without it every score
            # ties at the stale reading and the burst piles onto one
            # replica (the next real telemetry overwrites this)
            self._telemetry["queue_depth"] = (
                int(self._telemetry.get("queue_depth", 0)) + 1)
        return erid

    def adopt_request(self, prompt_token_ids, sampling_params=None,
                      generated_token_ids=(), stream=None,
                      streamed=None, arrive_t=None, arrival_index=None):
        generated = [int(t) for t in generated_token_ids]
        age_s = (max(0.0, self._clock() - float(arrive_t))
                 if arrive_t is not None else None)
        erid = self._call_admission("adopt", {
            "prompt": [int(t) for t in prompt_token_ids],
            "sp": wire.sp_to_dict(sampling_params),
            "generated": generated,
            "streamed": (len(generated) if streamed is None
                         else int(streamed)),
            "age_s": age_s,
            "arrival_index": (None if arrival_index is None
                              else int(arrival_index))})
        with self._lock:
            if stream is not None:
                self._streams[erid] = stream
            self._unfinished.add(erid)
            self._telemetry["queue_depth"] = (
                int(self._telemetry.get("queue_depth", 0)) + 1)
        return erid

    def step(self):
        """One remote engine step.  A missing response (crash, wedge,
        watchdog verdict) raises straight through to the router's
        failover path; a successful response updates every mirror and
        performs the one-and-only stream delivery for its tokens."""
        r = self.call("step")
        events = [(erid, (None if tok is None else int(tok)),
                   bool(fin)) for erid, tok, fin in r["events"]]
        deliveries = []
        with self._lock:
            for f in r.get("finished", ()):
                self.finished_requests[f["rid"]] = FinishedRemote(
                    f["rid"], f["tokens"], f.get("finish_reason"))
            for erid, tok, fin in events:
                s = self._streams.get(erid)
                if s is not None and (tok is not None or fin):
                    deliveries.append((s, tok, fin))
                if fin:
                    self._streams.pop(erid, None)
                    self._unfinished.discard(erid)
            tel = r.get("telemetry")
            if tel:
                self._telemetry.update(tel)
        # exactly-once delivery, outside the proxy lock (the router's
        # wrapper re-enters the router RLock; user streams are user
        # code): this response is consumed exactly once, and these
        # tokens exist in no other response
        for s, tok, fin in deliveries:
            s(None, tok, fin)
        return events

    def has_unfinished(self):
        with self._lock:
            return bool(self._unfinished)

    def release_waiting(self):
        reqs = self.call("release_waiting") or []
        out = []
        with self._lock:
            for f in reqs:
                out.append(FinishedRemote(f["rid"], f["tokens"], None))
                self._streams.pop(f["rid"], None)
                self._unfinished.discard(f["rid"])
        return out

    def warmup(self):
        # warmup is boot-phase work: the replica compiles or loads the
        # AOT cache inside the dispatch, beat-silent the whole time
        self._hold_verdict(self._config.rendezvous_timeout_s)
        try:
            return self.call("warmup",
                             timeout_s=self._config.rendezvous_timeout_s)
        finally:
            self._release_verdict()

    def shutdown(self):
        """Best-effort, short-fuse: the router calls this on DEAD
        replicas too, where nobody is listening."""
        with self._lock:
            dead = self._dead
        if dead:
            return
        try:
            self.call("shutdown",
                      timeout_s=min(2.0,
                                    self._config.collective_timeout_s))
        except Exception:
            pass

    def attach_stream(self, erid, stream):
        """Register a controller-side stream for a request that joined
        the remote engine OUTSIDE add/adopt — e.g. a disaggregated
        ``import_handoff`` (step responses only ever carry a token
        once, so attachment order cannot double-deliver)."""
        with self._lock:
            if stream is not None:
                self._streams[erid] = stream
            self._unfinished.add(erid)

    # -------------------------------------------------- telemetry mirror
    def note_telemetry(self, tel):
        """Heartbeat-borne telemetry (queue depth / page occupancy /
        health) refreshed by the controller's monitor poll — keeps
        routing scores current BETWEEN steps without an RPC."""
        if not tel:
            return
        with self._lock:
            self._telemetry.update(tel)

    @property
    def health(self):
        with self._lock:
            return _HealthShim(self._telemetry.get("health", 0))

    @property
    def queue_depth(self):
        with self._lock:
            return int(self._telemetry.get("queue_depth", 0))

    @property
    def page_occupancy(self):
        with self._lock:
            return float(self._telemetry.get("page_occupancy", 0.0))

    @property
    def num_running(self):
        with self._lock:
            return int(self._telemetry.get("num_running", 0))
