"""Disaggregated prefill/decode — page handoff between engines.

The split (PAPERS.md, the Gemma-on-TPU serving recipe): PREFILL
workers absorb the compute-bound prompt pass and fill (possibly
quantized ``(codes, scales)``, PR 13) KV pages; DECODE workers run
the memory-bound token loop.  The handoff moves the pages plus the
scheduler state — prompt/generated tokens, sampling params, stream
watermark, deadline AGE, arrival index — through
``LLMEngine.export_page_state`` / ``import_page_state`` and (across
processes) one ``<ns>/serve/handoff/<hid>`` KV blob in the
:func:`~paddle_tpu.serving.fleet.wire.pack_state` npz format.

Token identity is the whole contract: the deterministic ``(seed,
absolute position)`` sampler continues on the decode engine exactly
where the prefill engine stopped, so a disaggregated run is
token-identical to the monolithic engine on the same trace — and
since the import writes pages with eager scatters (no new compiled
program on either side), the bounded-compile contract survives,
verifiable from the observability recompile log.

:class:`DisaggregatedEngine` is the orchestration facade: engines
(or :class:`~paddle_tpu.serving.fleet.handle.RemoteEngineClient`
proxies — anything with the engine step surface) for each role, a
``generate()`` that admits on the prefill side, hands each request
off after its first token, and drains the decode side to completion.
A decode-side ``AdmissionRejected`` (no slot yet) leaves the exported
blob retryable — backpressure defers the handoff, never loses it.
"""
from __future__ import annotations

from paddle_tpu.observability import span
from paddle_tpu.resilience import fleet as _fleet
from paddle_tpu.serving.fleet import wire
from paddle_tpu.serving.scheduler import AdmissionRejected

__all__ = ["DisaggregatedEngine", "DisaggResult"]


def _is_remote(engine):
    return hasattr(engine, "call")


class DisaggResult:
    """Per-prompt outcome: where it finished (``"prefill"`` for
    single-token / early-stop requests that never needed the decode
    side, else ``"decode"``), the full token history, and the finish
    reason."""

    __slots__ = ("tokens", "finish_reason", "finished_on")

    def __init__(self, tokens, finish_reason, finished_on):
        self.tokens = [int(t) for t in tokens]
        self.finish_reason = finish_reason
        self.finished_on = finished_on


class DisaggregatedEngine:
    def __init__(self, prefill, decode, client=None, namespace_fn=None):
        self.prefill = prefill
        self.decode = decode
        self._client = client
        self._ns = namespace_fn or _fleet.coord_namespace
        self._next_hid = 0
        self.handoffs = 0
        self.handoff_bytes = 0

    # ------------------------------------------------------- transfer
    def export(self, request_id):
        """Pull `request_id` off the prefill side; returns an opaque
        retryable handle for :meth:`import_`.  Remote exports park the
        blob in the coordination KV under a fresh ``hid``; local ones
        carry the state dict (optionally bounced through the KV when a
        client is given, to exercise the real wire format)."""
        hid = f"h{self._next_hid}"
        self._next_hid += 1
        if _is_remote(self.prefill):
            r = self.prefill.call("export_handoff",
                                  {"request_id": request_id,
                                   "hid": hid})
            self.handoff_bytes += int(r.get("bytes", 0))
            return ("kv", hid)
        state = self.prefill.export_page_state(request_id)
        if self._client is not None:
            blob = wire.pack_state(state)
            self.handoff_bytes += len(blob)
            _fleet.kv_set_bytes(self._client,
                                wire.handoff_key(self._ns(), hid), blob)
            return ("kv", hid)
        return ("state", state)

    def import_(self, handle, stream=None):
        """Land an exported request on the decode side; raises
        ``AdmissionRejected`` with the handle still valid (retry after
        the decode side frees a slot).  Returns the decode-side rid."""
        kind, payload = handle
        if _is_remote(self.decode):
            if kind != "kv":
                raise ValueError("a remote decode engine imports only "
                                 "KV-parked handoffs")
            rid = self.decode.call("import_handoff", {"hid": payload})
            self.decode.attach_stream(rid, stream)
        else:
            if kind == "kv":
                key = wire.handoff_key(self._ns(), payload)
                blob = _fleet.kv_get_bytes(
                    self._client, key, site="serving.fleet.handoff")
                state = wire.unpack_state(blob)
                rid = self.decode.import_page_state(state,
                                                    stream=stream)
                try:
                    self._client.key_value_delete(key)
                except Exception:
                    pass
            else:
                rid = self.decode.import_page_state(payload,
                                                    stream=stream)
        self.handoffs += 1
        with span("serving.disagg.handoff", rid=rid, kind=kind):
            pass
        return rid

    # ------------------------------------------------------- generate
    def generate(self, prompts, sampling_params=None):
        """Serve `prompts` through the split: admit on the prefill
        side, hand each request off after its FIRST token (the
        prefill-produced one), drain the decode side; returns one
        :class:`DisaggResult` per prompt in input order."""
        if prompts and isinstance(prompts[0], int):
            raise TypeError("generate expects a LIST of prompts "
                            "(each a list of token ids)")
        if isinstance(sampling_params, (list, tuple)):
            if len(sampling_params) != len(prompts):
                raise ValueError("one SamplingParams per prompt "
                                 "required")
            sps = list(sampling_params)
        else:
            sps = [sampling_params] * len(prompts)
        order = []                 # prefill rid, in input order
        for p, sp in zip(prompts, sps):
            order.append(self.prefill.add_request(
                [int(t) for t in p], sp))
        pending = set(order)       # still on the prefill side
        ready = []                 # (prefill_rid, export handle)
        mapping = {}               # decode rid -> prefill rid
        results = {}               # prefill rid -> DisaggResult
        live_decode = set()
        stall = 0
        while pending or ready or live_decode:
            progressed = False
            if pending:
                for rid, tok, fin in self.prefill.step():
                    if rid not in pending:
                        continue
                    progressed = True
                    if fin:
                        req = self.prefill.finished_requests.pop(
                            rid, None)
                        results[rid] = DisaggResult(
                            req.output_token_ids if req else (),
                            getattr(req, "finish_reason", None),
                            "prefill")
                        pending.discard(rid)
                    elif tok is not None:
                        # first token landed: the request is DECODE-
                        # state on the prefill engine — export now
                        # (frees its prefill pages) and queue the
                        # import
                        ready.append((rid, self.export(rid)))
                        pending.discard(rid)
            if ready:
                still = []
                for rid, handle in ready:
                    try:
                        drid = self.import_(handle)
                    except AdmissionRejected:
                        still.append((rid, handle))  # retry next round
                        continue
                    progressed = True
                    mapping[drid] = rid
                    live_decode.add(drid)
                ready = still
            if live_decode:
                for drid, tok, fin in self.decode.step():
                    if not fin or drid not in live_decode:
                        continue
                    progressed = True
                    req = self.decode.finished_requests.pop(drid, None)
                    rid = mapping.pop(drid)
                    results[rid] = DisaggResult(
                        req.output_token_ids if req else (),
                        getattr(req, "finish_reason", None), "decode")
                    live_decode.discard(drid)
            # a full round with no event anywhere means the split is
            # wedged (e.g. decode forever refusing imports) — fail
            # loudly rather than spin
            stall = 0 if progressed else stall + 1
            if stall > 1024:
                # abandoned-handoff reap (protolint PL101): every
                # handle still awaiting import has its page state
                # parked in the coordination KV; the caller is about
                # to fail this batch over, and nobody will ever
                # import these hids — without the delete the blobs
                # (the LARGEST keys in the store, full page state)
                # outlive the batch until the end-of-run namespace
                # reap.  Best effort: the import side's own
                # delete-on-consume makes a double delete a no-op.
                if self._client is not None:
                    for _rid, handle in ready:
                        kind, payload = handle
                        if kind != "kv":
                            continue
                        try:
                            self._client.key_value_delete(
                                wire.handoff_key(self._ns(), payload))
                        except Exception:
                            pass
                raise RuntimeError(
                    f"disaggregated generate stalled: {len(pending)} "
                    f"prefilling, {len(ready)} awaiting import, "
                    f"{len(live_decode)} decoding")
        return [results[rid] for rid in order]
