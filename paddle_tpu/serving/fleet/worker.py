"""Serving-fleet worker — the per-process entrypoint of the
multi-host serving chaos proof and the ``--worker-fleetserving``
bench lane.

Launched one OS process per coordination rank through ``python -m
paddle_tpu.distributed.launch <this file> <scenario.json>``.  The
scenario file assigns roles by rank:

- ``controller_rank`` (global rank 0, so the coordination service
  outlives every peer): computes the MONOLITHIC reference run first
  (same seed → same weights; its warmup also compiles the program
  ladder into the shared AOT cache so every replica boots warm), then
  drives a :class:`~paddle_tpu.serving.fleet.controller.ServingFleet`
  through the full trace, the disaggregated prefill/decode phase, and
  the per-replica compile audit, and writes ``controller.json``;
- every ``worker_ranks`` / ``spare_ranks`` member runs a
  :class:`~paddle_tpu.serving.fleet.server.ReplicaServer` (spares
  idle until a respawn's ``boot`` claims them) with a heartbeat
  publisher shipping live engine telemetry, and writes
  ``replica-rank<N>.json`` on clean shutdown.

Chaos comes from the scenario's ``faults`` table (rank → FaultSpec
dicts, fired at the ``serving.fleet.step`` site): ``rank_kill``
SIGKILLs a replica mid-decode, ``wedge`` SIGSTOPs one — the parent
test must SIGKILL a wedged child once ``controller.json`` appears.

Everything exits via ``fleet.finalize()`` + ``os._exit`` — after a
peer died by design, the jax shutdown barrier can never complete.
"""
import json
import os
import sys
import time


def _load_cfg():
    with open(sys.argv[1]) as fh:
        return json.load(fh)


def _write_result(out_dir, name, result):
    path = os.path.join(out_dir, name)
    with open(path + ".tmp", "w") as fh:
        json.dump(result, fh, default=str)
    os.replace(path + ".tmp", path)


def _sps(dicts):
    from paddle_tpu.serving.fleet import wire
    return [wire.sp_from_dict(d) for d in dicts]


# ------------------------------------------------------------ replica
def run_replica(cfg, grank):
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.resilience import fleet as flt
    from paddle_tpu.serving.fleet.server import ReplicaServer

    specs = [faultinject.FaultSpec(**d)
             for d in (cfg.get("faults") or {}).get(str(grank), [])]
    if specs:
        faultinject.install(faultinject.FaultInjector(
            faultinject.FaultPlan(specs, seed=grank,
                                  name="fleetserving-chaos")))

    def factory(payload):
        import paddle_tpu as P
        from paddle_tpu import serving
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        P.seed(int(cfg["seed"]))       # identical weights fleet-wide
        model = GPTForCausalLM(GPTConfig(**cfg["model"]))
        return serving.LLMEngine(
            model, serving.EngineConfig(**cfg["engine"]),
            program_cache=cfg.get("cache_dir"),
            metrics_name=f"serving.fleet.r{grank}")

    server = ReplicaServer(flt._client(), grank, factory)
    flt.install_publisher(
        flt.HeartbeatPublisher(payload_fn=server.telemetry).start())
    server.serve()

    result = {"role": "replica", "rank": grank, "steps": server.steps,
              "requests_served": server.requests_served}
    eng = server.engine
    if eng is not None:
        m = eng.metrics
        result.update(compiled=int(m.compile_count),
                      bound=int(m.compile_bound),
                      cache_loads=int(m.aot_cache_loads),
                      generated_tokens=int(m.generated_tokens))
    _write_result(cfg["out_dir"], f"replica-rank{grank}.json", result)


# ----------------------------------------------------- traffic mode
class _FleetStepAdapter:
    """Router facade for the traffic driver over a ServingFleet:
    stepping must go through :meth:`ServingFleet.step` (watchdog
    verdicts + failover live there), everything else — admission,
    finished results, telemetry — is the fleet's stock router."""

    def __init__(self, sfleet):
        self._sfleet = sfleet

    def step(self):
        return self._sfleet.step()

    def __getattr__(self, name):
        return getattr(self._sfleet.router, name)


def run_traffic_controller(cfg, grank):
    """Traffic-mode controller (scenario has a ``traffic`` key): replay
    a seeded :class:`TrafficSpec` through the multi-process fleet —
    arrivals on the driver's virtual clock, service and watchdog
    verdicts on the wall clock — and report the driver's goodput /
    token-loss accounting next to the fleet's failover evidence.  This
    is how the PR 14-16 chaos proofs become capacity-planning numbers:
    the scenario's ``faults`` table SIGKILLs / wedges replicas
    mid-run, and the report must keep goodput within the declared
    budget with zero token loss."""
    import paddle_tpu as P
    from paddle_tpu import serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.resilience import fleet as flt
    from paddle_tpu.serving import traffic
    from paddle_tpu.serving.fleet import (FleetServingConfig,
                                          ServingFleet)
    from paddle_tpu.serving.router.router import RouterConfig

    # warm the shared AOT cache first, so every replica boot —
    # respawns included — classifies warm (same seed → same weights)
    P.seed(int(cfg["seed"]))
    model = GPTForCausalLM(GPTConfig(**cfg["model"]))
    warm = serving.LLMEngine(
        model, serving.EngineConfig(**cfg["engine"]),
        program_cache=cfg.get("cache_dir"),
        metrics_name="serving.fleet.warmcache")
    warm.warmup()
    warm.shutdown()

    flt.install_publisher(flt.HeartbeatPublisher().start())
    sfleet = ServingFleet(
        flt._client(),
        FleetServingConfig(cfg["worker_ranks"],
                           cfg.get("spare_ranks", ()),
                           boot_payload={}),
        router_config=RouterConfig(sleep=lambda s: None))

    spec = traffic.TrafficSpec.from_dict(cfg["traffic"])
    clock = traffic.VirtualClock()
    driver = traffic.TrafficDriver(
        _FleetStepAdapter(sfleet), spec, clock,
        quantum_s=float(cfg.get("quantum_s", 0.01)),
        name="fleet-traffic")
    report = driver.run()
    driver.release()
    snap = sfleet.router.snapshot()
    result = {"role": "controller", "rank": grank,
              "traffic": report,
              "detections": sfleet.detections(),
              "respawn_ms": sfleet.respawn_ms,
              "boots": [dict(h.boot_info or {})
                        for h in sfleet.router.replicas],
              "snapshot": {k: snap.get(k)
                           for k in ("failovers", "respawns",
                                     "adoptions", "spillovers",
                                     "requests_finished")}}
    sfleet.shutdown()
    _write_result(cfg["out_dir"], "controller.json", result)


# --------------------------------------------------------- controller
def run_controller(cfg, grank):
    import paddle_tpu as P
    from paddle_tpu import serving
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.resilience import fleet as flt
    from paddle_tpu.serving.fleet import (DisaggregatedEngine,
                                          FleetServingConfig,
                                          ServingFleet)
    from paddle_tpu.serving.router.router import RouterConfig
    from paddle_tpu.serving.scheduler import AdmissionRejected

    prompts = [[int(t) for t in p] for p in cfg["prompts"]]
    sps = _sps(cfg["sampling"])
    dprompts = [[int(t) for t in p]
                for p in cfg.get("disagg_prompts", [])]
    dsps = _sps(cfg.get("disagg_sampling", []))

    # ---- monolithic reference: the zero-loss yardstick (same seed →
    # same weights), and its warmup compiles the ladder INTO the
    # shared AOT cache so every replica boot — respawns included —
    # classifies warm
    P.seed(int(cfg["seed"]))
    model = GPTForCausalLM(GPTConfig(**cfg["model"]))
    ref_engine = serving.LLMEngine(
        model, serving.EngineConfig(**cfg["engine"]),
        program_cache=cfg.get("cache_dir"),
        metrics_name="serving.fleet.reference")
    ref_engine.warmup()
    ref = ref_engine.generate(prompts, sps)
    dref = ref_engine.generate(dprompts, dsps) if dprompts else []
    result = {"role": "controller", "rank": grank,
              "ref": [{"tokens": r.output_token_ids,
                       "finish_reason": r.finish_reason} for r in ref],
              "disagg_ref": [{"tokens": r.output_token_ids,
                              "finish_reason": r.finish_reason}
                             for r in dref]}
    ref_engine.shutdown()

    flt.install_publisher(flt.HeartbeatPublisher().start())
    sfleet = ServingFleet(
        flt._client(),
        FleetServingConfig(cfg["worker_ranks"],
                           cfg.get("spare_ranks", ()),
                           boot_payload={}),
        router_config=RouterConfig(sleep=lambda s: None))

    # per-request stream collectors: the exactly-once evidence (the
    # streamed prefix must equal the final token history, with exactly
    # one fin, across any number of mid-stream failovers)
    streams = {}

    def _collector():
        rec = {"tokens": [], "fins": 0}

        def _stream(rid, tok, fin):
            if tok is not None:
                rec["tokens"].append(int(tok))
            if fin:
                rec["fins"] += 1

        return rec, _stream

    t0 = time.perf_counter()
    rids = []
    for p, sp in zip(prompts, sps):
        rec, stream = _collector()
        deadline = time.monotonic() + 30.0
        while True:
            try:
                rid = sfleet.router.add_request(p, sp, stream=stream)
                break
            except AdmissionRejected:
                if time.monotonic() > deadline:
                    raise
                sfleet.step()      # productive backpressure wait
        rids.append(rid)
        streams[rid] = rec

    budget = float(cfg.get("serve_budget_s", 120.0))
    while sfleet.router.has_unfinished():
        if time.perf_counter() - t0 > budget:
            break                  # report partial state, never hang
        sfleet.step()
    serve_s = time.perf_counter() - t0

    fleet_res = []
    total = 0
    for rid in rids:
        rr = sfleet.router.finished_results.pop(rid, None)
        rec = streams[rid]
        toks = (None if rr is None
                else [int(t) for t in rr.output_token_ids])
        total += len(toks or ())
        fleet_res.append({
            "rid": rid, "tokens": toks,
            "finish_reason": None if rr is None else rr.finish_reason,
            "migrations": None if rr is None else rr.migrations,
            "stream_tokens": rec["tokens"],
            "stream_fins": rec["fins"]})
    result["fleet"] = fleet_res
    result["serve_s"] = round(serve_s, 3)
    result["tokens_per_s"] = (round(total / serve_s, 2)
                              if serve_s > 0 else None)

    # ---- disaggregated prefill/decode across two live replicas
    live = [h for h in sfleet.router.replicas if h.alive]
    if dprompts and live:
        prefill, decode = live[0].engine, live[-1].engine
        disagg = DisaggregatedEngine(prefill, decode,
                                     client=sfleet.client)
        dres = disagg.generate(dprompts, dsps)
        result["disagg"] = [{"tokens": r.tokens,
                             "finish_reason": r.finish_reason,
                             "finished_on": r.finished_on}
                            for r in dres]
        result["disagg_ranks"] = [prefill.rank, decode.rank]
        result["handoffs"] = disagg.handoffs
        result["handoff_bytes"] = disagg.handoff_bytes

    audits = {}
    for h in live:
        try:
            audits[str(h.engine.rank)] = h.engine.call("audit")
        except Exception as e:            # audit must not mask results
            audits[str(h.engine.rank)] = {"error": str(e)}
    result["audits"] = audits
    result["detections"] = sfleet.detections()
    result["respawn_ms"] = sfleet.respawn_ms
    result["boots"] = [dict(h.boot_info or {})
                       for h in sfleet.router.replicas]
    snap = sfleet.router.snapshot()
    result["snapshot"] = {k: snap.get(k)
                          for k in ("failovers", "respawns",
                                    "adoptions", "spillovers",
                                    "requests_finished")}
    result["assigned"] = {str(i): sfleet.rank_of(i)
                          for i in range(len(cfg["worker_ranks"]))}
    # flight-recorder summaries (full postmortem-r<N>.json files live
    # in the spool dir): what each DEAD-verdict rank was doing
    result["postmortems"] = {
        str(r): {"in_flight_requests": pm["in_flight_requests"],
                 "in_flight_traces": pm["in_flight_traces"],
                 "spans_total": pm["spans_total"],
                 "path": pm.get("path")}
        for r, pm in sorted(sfleet.postmortems.items())}

    sfleet.shutdown()
    _write_result(cfg["out_dir"], "controller.json", result)


def _detach_local_backend():
    """Detach XLA from the multi-process world, keeping ONLY the
    coordination client.  Replicas are independent single-host engines
    — the fleet shares a KV fabric, never an XLA collective domain —
    and a single-host backend is what makes AOT-cache executables
    PORTABLE across the fleet: a multihost backend pins global device
    ids into serialized programs, which no OTHER process can address
    ("Device assignment ... does not have any local devices"), so
    every boot would cold-compile and warm respawn would be a lie."""
    import jax
    from jax._src import distributed as jd
    from jax._src import xla_bridge as xb
    client = jd.global_state.client
    jd.global_state.client = None
    jd.global_state.process_id = 0
    jd.global_state.num_processes = 1
    xb._clear_backends()
    jax.devices()            # rebuild: plain single-host CPU client
    jd.global_state.client = client


def main():
    cfg = _load_cfg()
    import jax
    import numpy as np
    from jax.sharding import Mesh

    import paddle_tpu as P  # noqa: F401  (installs shims)
    from paddle_tpu.analysis import kv_tracer
    from paddle_tpu.distributed import mesh as _mesh
    from paddle_tpu.resilience import fleet as flt

    kv_tracer.arm_from_env()   # no-op unless PTPU_KV_TRACE_DIR is set
    grank = jax.process_index()
    # pin the TRUE world before detaching: after the detach,
    # jax.process_index()/count() read the single-host backend, so the
    # fleet layer must carry the launch-time membership explicitly
    flt._set_world(flt.WorldView(range(jax.process_count()), grank,
                                 launch_id=flt._ensure_launch_id()))
    # telemetry spooling (no-op unless PTPU_OBS_SPOOL_DIR is set): the
    # KV clock handshake runs against the still-attached coordination
    # client, so every rank's spool aligns to the controller's clock
    from paddle_tpu.observability import fleettrace
    fleettrace.arm_from_env(rank=grank, client=flt._client())
    _detach_local_backend()
    _mesh.set_mesh(Mesh(np.asarray(jax.local_devices()), ("dp",)))
    if grank == int(cfg.get("controller_rank", 0)):
        if cfg.get("traffic"):
            run_traffic_controller(cfg, grank)
        else:
            run_controller(cfg, grank)
        fleettrace.disarm()    # flush the final metrics snapshot
        # bounded linger: dead-by-design peers never check out
        flt.finalize(timeout_s=float(cfg.get("finalize_s", 6.0)))
    else:
        run_replica(cfg, grank)
        fleettrace.disarm()
        flt.finalize()
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
