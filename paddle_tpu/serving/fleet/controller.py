"""ServingFleet — the controller that fronts remote replica workers
with the stock PR 11 Router.

Topology (docs/serving.md "Multi-host fleet"): the controller process
owns the Router, the fleet watchdog (:class:`FleetMonitor`) and one
:class:`~paddle_tpu.serving.fleet.handle.RemoteEngineClient` per live
replica; replica worker processes each run a
:class:`~paddle_tpu.serving.fleet.server.ReplicaServer` around a real
engine.  The router's ``engine_factory`` is where elasticity lives:

- first boot of replica slot ``i`` claims ``worker_ranks[i]``;
- a RESPAWN of slot ``i`` (its previous rank is dead — SIGKILL,
  SIGSTOP verdict, or drain-out) claims the next prespawned SPARE
  rank instead: respawn-elsewhere.  The spare worker was idle until
  now; its ``boot`` builds an engine against the SHARED AOT program
  cache directory, so the router's ``warmup()`` classifies the boot
  warm (``compiled == 0 and cache_loads > 0``) and the replacement
  rejoins in cache-load time, not compile time (the 38× warm-boot
  lever, docs/serving.md "AOT program cache");
- a factory call with the spare pool empty raises, which the router
  answers by REQUEUEING the respawn and retrying next step — capacity
  degrades gracefully instead of the fleet dying.

The watchdog feeds failure detection two ways: every pending RPC's
``abort_if`` aborts on a DEAD verdict (a wedged replica fails the
in-flight ``step()`` within one KV slice of the verdict), and
heartbeat-borne telemetry (queue depth / page occupancy / health)
refreshes each proxy's routing score between steps without any RPC.
"""
from __future__ import annotations

import threading
import time

from paddle_tpu.observability import span
from paddle_tpu.resilience import fleet as _fleet
from paddle_tpu.serving.fleet.handle import RemoteEngineClient

__all__ = ["ServingFleet", "FleetServingConfig"]


class FleetServingConfig:
    """Controller wiring: which coordination ranks serve, which are
    spares, and what the replica ``boot`` verb should build.

    - `worker_ranks`: the initially-ACTIVE replica ranks, one router
      replica slot each.
    - `spare_ranks`: prespawned idle workers, claimed in order by
      respawns (respawn-elsewhere).
    - `boot_payload`: opaque dict handed to the worker's engine
      factory (model/engine config, AOT cache dir, seed — the worker
      entrypoint decides its meaning).
    - `rpc_timeout_s`: per-RPC deadline (defaults to the fleet
      config's ``collective_timeout_s``).
    """

    def __init__(self, worker_ranks, spare_ranks=(), boot_payload=None,
                 fleet_config=None, rpc_timeout_s=None):
        self.worker_ranks = [int(r) for r in worker_ranks]
        self.spare_ranks = [int(r) for r in spare_ranks]
        if not self.worker_ranks:
            raise ValueError("at least one worker rank is required")
        overlap = set(self.worker_ranks) & set(self.spare_ranks)
        if overlap:
            raise ValueError(f"ranks {sorted(overlap)} are both "
                             f"active and spare")
        self.boot_payload = dict(boot_payload or {})
        self.fleet_config = fleet_config or _fleet.get_config()
        if rpc_timeout_s is not None:
            # narrow ONLY the RPC deadline, not the shared fleet config
            import copy
            fc = copy.copy(self.fleet_config)
            fc.collective_timeout_s = float(rpc_timeout_s)
            self.fleet_config = fc


class ServingFleet:
    def __init__(self, client, config, *, router_config=None,
                 monitor=None, namespace_fn=None, start_monitor=True):
        self.client = client
        self.config = config
        self._ns = namespace_fn or _fleet.coord_namespace
        self._lock = threading.Lock()
        self._spares = list(config.spare_ranks)
        self._assigned = {}       # replica index -> current rank
        self._retired = []        # (index, rank) of replaced workers
        self.proxies = {}         # rank -> RemoteEngineClient
        self.respawn_ms = []      # boot wall time of each respawn
        self.postmortems = {}     # rank -> flight-record dict
        self.monitor = monitor
        if self.monitor is None:
            self.monitor = _fleet.FleetMonitor(
                client=client, config=config.fleet_config)
        # crash flight recorder: a DEAD verdict finalizes the dead
        # rank's telemetry spool into a post-mortem (last spans, last
        # metric snapshot, in-flight request ids).  Chained IN FRONT of
        # any hook an externally-provided monitor already has.
        prev_on_dead = self.monitor.on_dead

        def _on_dead(ranks, _prev=prev_on_dead):
            self._flight_record(ranks)
            if _prev is not None:
                _prev(ranks)

        self.monitor.on_dead = _on_dead
        if start_monitor:
            self.monitor.start()
        # import here so a fleet-less serving install stays light
        from paddle_tpu.serving.router.router import Router, RouterConfig
        self.router = Router(
            engine_factory=self._factory,
            num_replicas=len(config.worker_ranks),
            config=router_config or RouterConfig())

    # ---------------------------------------------------- elasticity
    def _factory(self, index):
        """Router boot hook: claim a rank for replica slot `index` —
        the slot's initial rank on first boot, the next SPARE on a
        respawn — and drive the worker's ``boot`` verb."""
        t0 = time.perf_counter()
        with self._lock:
            respawn = index in self._assigned
            prev_rank = self._assigned.get(index)
            if respawn:
                if not self._spares:
                    # leave _assigned/_retired untouched: the router
                    # requeues this respawn and retries next step
                    raise RuntimeError(
                        f"replica slot {index} needs a respawn but the "
                        f"spare pool is empty — retrying next step")
                self._retired.append((index, prev_rank))
                rank = self._spares.pop(0)
            else:
                rank = self.config.worker_ranks[index]
            self._assigned[index] = rank
        proxy = RemoteEngineClient(
            self.client, rank, namespace_fn=self._ns,
            config=self.config.fleet_config,
            abort_if=lambda r=rank: self.monitor.is_dead(r),
            hold_verdict=lambda s, r=rank:
                self.monitor.hold_verdict(r, s),
            release_verdict=lambda r=rank:
                self.monitor.release_verdict_hold(r))
        payload = dict(self.config.boot_payload)
        payload.update(replica_index=int(index), rank=int(rank),
                       respawn=bool(respawn))
        # verdicts held for the boot window: the worker goes silent
        # while it builds its engine, and a spurious terminal DEAD
        # mid-boot would wedge the rank forever (the rendezvous
        # deadline below still bounds a boot that never completes)
        self.monitor.hold_verdict(
            rank, self.config.fleet_config.rendezvous_timeout_s)
        try:
            proxy.call("boot", payload,
                       timeout_s=self.config.fleet_config
                       .rendezvous_timeout_s)
        except Exception:
            # un-claim on boot failure: a transient boot abort must
            # not leak the claim — the spare goes back in the pool
            # (same one is retried next attempt) and the slot's
            # previous owner is restored, or every failed first boot
            # would burn a spare until the pool reads empty
            with self._lock:
                if respawn:
                    self._spares.insert(0, rank)
                    self._retired.pop()
                    self._assigned[index] = prev_rank
                else:
                    self._assigned.pop(index, None)
            raise
        finally:
            self.monitor.release_verdict_hold(rank)
        with self._lock:
            self.proxies[rank] = proxy
        if respawn:
            ms = round((time.perf_counter() - t0) * 1e3, 3)
            with self._lock:
                self.respawn_ms.append(ms)
            with span("serving.fleet.respawn", replica=index,
                      rank=rank, boot_ms=ms):
                pass
        return proxy

    # ----------------------------------------------- flight recorder
    def _flight_record(self, ranks):
        """Watchdog ``on_dead`` hook (monitor thread, outside its
        lock): recover each dead rank's post-mortem from its telemetry
        spool.  A no-op when spooling is not armed fleet-wide."""
        import os
        from paddle_tpu.observability import fleettrace
        spool_dir = os.environ.get(fleettrace.SPOOL_ENV)
        if not spool_dir or not os.path.isdir(spool_dir):
            return
        for rank in ranks:
            if rank in self.postmortems:
                continue
            try:
                report = fleettrace.flight_record(spool_dir, rank)
            except Exception:
                continue        # a torn spool must not break failover
            if report is None:
                continue
            self.postmortems[int(rank)] = report
            # the failover span's post-mortem rider: WHAT the rank was
            # doing when it died, on the controller's own timeline
            with span("serving.fleet.postmortem", rank=int(rank),
                      in_flight=len(report["in_flight_requests"]),
                      spans=report["spans_total"],
                      path=report.get("path")):
                pass

    # ------------------------------------------------------- serving
    def step(self):
        """One fleet iteration: refresh heartbeat-borne telemetry into
        the proxies (keeps routing scores live between steps), then
        one router step."""
        self.refresh_telemetry()
        return self.router.step()

    def refresh_telemetry(self):
        with self._lock:
            items = list(self.proxies.items())
        for rank, proxy in items:
            tel = self.monitor.telemetry(rank)
            if tel is not None:
                proxy.note_telemetry(tel)

    def rank_of(self, index):
        with self._lock:
            return self._assigned.get(int(index))

    def proxy_for_rank(self, rank):
        with self._lock:
            return self.proxies.get(int(rank))

    def detections(self):
        """Every watchdog-driven RPC abort the proxies saw:
        ``[{rank, verdict, waited_s, detect_s, ...}]`` — the failover-
        detection evidence the chaos proof and bench lane report."""
        out = []
        with self._lock:
            proxies = list(self.proxies.values())
        for p in proxies:
            if p.last_timeout is not None:
                d = dict(p.last_timeout)
                d["rank"] = p.rank
                d["detect_s"] = p.detect_s
                out.append(d)
        return out

    def shutdown(self, stop_monitor=True):
        """Best-effort fleet teardown: shut the router down (which
        short-fuse ``shutdown``s each live proxy), then every worker
        that never joined the router (unused spares), then the
        watchdog."""
        try:
            self.router.shutdown()
        except Exception:
            pass
        with self._lock:
            booted = set(self.proxies)
            idle = [r for r in self._spares if r not in booted]
        for rank in idle:
            proxy = RemoteEngineClient(
                self.client, rank, namespace_fn=self._ns,
                config=self.config.fleet_config)
            try:
                proxy.call("shutdown", timeout_s=2.0)
            except Exception:
                pass
        if stop_monitor:
            try:
                self.monitor.stop()
            except Exception:
                pass
