"""Serving-fleet wire protocol — a tiny RPC over the coordination KV.

Every replica worker owns one request/response lane in the fleet
namespace (docs/serving.md "Multi-host fleet"):

- request  ``<ns>/serve/r<rank>/req/<seq>``   controller → replica
- response ``<ns>/serve/r<rank>/rsp/<seq>``   replica → controller

``seq`` is a per-lane monotonic counter owned by the controller, so
the lane is strictly ordered and exactly-once by construction: each
side deletes a key the moment it has consumed it (the coordination
service's ``key_value_delete``), and a response is read exactly once
before the next request is posted.  Messages are JSON dicts
``{"m": method, "p": payload}`` (plus ``"tc"``, the request's
distributed-trace context, when one is ambient at the caller — see
:mod:`paddle_tpu.observability.fleettrace`) /
``{"ok": bool, "r": result}`` —
bulk binary (the disaggregated page handoff) never rides the RPC
lane; it goes to its own ``<ns>/serve/handoff/<hid>`` key as raw npz
bytes and the RPC carries only the ``hid``.

The controller-side wait is :func:`resilience.fleet.kv_get_bytes`
with ``abort_if`` wired to the fleet watchdog's DEAD verdict — a
wedged replica (SIGSTOP: alive to the OS, silent to the fleet) fails
the pending call with a :class:`~paddle_tpu.resilience.fleet.
CollectiveTimeout` carrying ``verdict="dead-verdict"`` within one KV
slice of the verdict, instead of burning the full RPC budget.

Clock discipline: ``arrive_t`` values are per-process
``metrics.clock`` readings and NEVER cross the wire; deadlines travel
as ``age_s`` (time already consumed) and are re-anchored against the
receiver's clock — which is exactly what keeps a ``deadline_s`` TTL
counting from FIRST arrival across any number of migrations.
"""
from __future__ import annotations

import io
import json

import numpy as np

from paddle_tpu.observability import spans as _spans
from paddle_tpu.resilience import fleet as _fleet
from paddle_tpu.serving.request import SamplingParams
from paddle_tpu.serving.scheduler import AdmissionRejected

__all__ = [
    "RemoteReplicaError", "req_key", "rsp_key", "handoff_key",
    "sp_to_dict", "sp_from_dict", "post_request", "await_response",
    "read_request", "post_response", "pack_state", "unpack_state",
]

RPC_SITE = "serving.fleet.rpc"


class RemoteReplicaError(RuntimeError):
    """A replica-side exception that has no typed equivalent on the
    controller (typed backpressure — ``AdmissionRejected`` /
    ``ValueError`` — re-raises as itself; everything else lands here
    with the remote type name in the message)."""


def req_key(namespace, rank, seq):
    return f"{namespace}/serve/r{int(rank)}/req/{int(seq)}"


def rsp_key(namespace, rank, seq):
    return f"{namespace}/serve/r{int(rank)}/rsp/{int(seq)}"


def handoff_key(namespace, hid):
    return f"{namespace}/serve/handoff/{hid}"


# ------------------------------------------------------- marshalling
def sp_to_dict(sp):
    if sp is None:
        return None
    return {"max_new_tokens": sp.max_new_tokens,
            "temperature": sp.temperature,
            "top_k": sp.top_k, "top_p": sp.top_p, "seed": sp.seed,
            "eos_token_id": sp.eos_token_id,
            "deadline_s": sp.deadline_s}


def sp_from_dict(d):
    if d is None:
        return None
    return SamplingParams(**d)


def _marshal_error(exc):
    err = {"type": type(exc).__name__, "msg": str(exc)}
    if isinstance(exc, AdmissionRejected):
        err["reason"] = exc.reason
    return err


def _unmarshal_error(err):
    t = err.get("type")
    if t == "AdmissionRejected":
        raise AdmissionRejected(err.get("reason", "remote"),
                                err.get("msg", ""))
    if t == "ValueError":
        raise ValueError(err.get("msg", ""))
    raise RemoteReplicaError(f"{t}: {err.get('msg', '')}")


# ------------------------------------------------- controller side
def post_request(client, namespace, rank, seq, method, payload,
                 ctx=None):
    """Post one RPC.  The caller's ambient
    :class:`~paddle_tpu.observability.TraceContext` (or an explicit
    `ctx`) rides the envelope as ``"tc"`` so the replica's spans record
    under the originating request's trace — absent entirely (and
    byte-identical to the pre-tracing envelope) when no trace is
    active."""
    if ctx is None:
        ctx = _spans.current_context()
    msg = {"m": str(method), "p": payload}
    if ctx is not None:
        msg["tc"] = ctx.to_dict()
    _fleet.kv_set_bytes(client, req_key(namespace, rank, seq),
                        json.dumps(msg).encode())


def await_response(client, namespace, rank, seq, timeout_s, *,
                   abort_if=None, config=None):
    """Block for the replica's response to `seq`; raises
    ``CollectiveTimeout`` (watchdog verdict or deadline) or the
    re-raised remote exception; returns the result value."""
    key = rsp_key(namespace, rank, seq)
    raw = _fleet.kv_get_bytes(client, key, timeout_s, site=RPC_SITE,
                              missing_rank=int(rank),
                              abort_if=abort_if, config=config)
    try:
        client.key_value_delete(key)
    except Exception:
        pass            # namespace reap at finalize() catches leaks
    rsp = json.loads(bytes(raw).decode())
    if not rsp.get("ok"):
        _unmarshal_error(rsp.get("err", {}))
    return rsp.get("r")


# ---------------------------------------------------- replica side
def read_request(client, namespace, rank, seq, timeout_s, *,
                 config=None):
    """Replica-side blocking read of request `seq` (short, so the
    serve loop can interleave heartbeat/stop checks); raises
    ``CollectiveTimeout`` on an empty slice window."""
    key = req_key(namespace, rank, seq)
    raw = _fleet.kv_get_bytes(client, key, timeout_s,
                              site="serving.fleet.recv",
                              config=config)
    try:
        client.key_value_delete(key)
    except Exception:
        pass
    msg = json.loads(bytes(raw).decode())
    return (msg["m"], msg.get("p"),
            _spans.TraceContext.from_dict(msg.get("tc")))


def post_response(client, namespace, rank, seq, result=None,
                  error=None):
    rsp = ({"ok": False, "err": _marshal_error(error)}
           if error is not None else {"ok": True, "r": result})
    _fleet.kv_set_bytes(client, rsp_key(namespace, rank, seq),
                        json.dumps(rsp).encode())


# --------------------------------------- page-handoff serialization
def pack_state(state):
    """``LLMEngine.export_page_state`` dict → one npz byte blob (JSON
    header under ``__meta__``, per-layer KV blocks as arrays) — the
    handoff wire format (docs/serving.md)."""
    arrays = {}
    for li, blk in enumerate(state["layers"]):
        for name, arr in blk.items():
            arrays[f"L{li}.{name}"] = np.asarray(arr)
    meta = {k: v for k, v in state.items() if k != "layers"}
    meta["num_layers"] = len(state["layers"])
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def unpack_state(blob):
    """Inverse of :func:`pack_state`."""
    with np.load(io.BytesIO(bytes(blob))) as z:
        meta = json.loads(bytes(z["__meta__"].tobytes()).decode())
        n = int(meta.pop("num_layers"))
        layers = []
        for li in range(n):
            prefix = f"L{li}."
            layers.append({k[len(prefix):]: z[k] for k in z.files
                           if k.startswith(prefix)})
    state = dict(meta)
    state["layers"] = layers
    return state
