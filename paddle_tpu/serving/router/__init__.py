"""paddle_tpu.serving.router — telemetry-driven multi-replica serving.

A :class:`Router` fronts N :class:`~paddle_tpu.serving.LLMEngine`
replicas and balances admissions on the fleet's live telemetry (queue
depth, page occupancy, health state), with sticky request→replica
affinity, ``AdmissionRejected``-aware spillover + retry, failover
migration that loses no tokens, and elastic drain/respawn — replicas
booting WARM from the persisted AOT program cache
(:mod:`paddle_tpu.serving.aot_cache`).

Quickstart::

    from paddle_tpu import serving
    from paddle_tpu.serving.router import Router
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny

    router = Router(GPTForCausalLM(gpt3_tiny()),
                    serving.EngineConfig(max_num_seqs=8,
                                         max_model_len=128),
                    num_replicas=3,
                    program_cache="/var/cache/paddle_tpu/aot")
    results = router.generate(
        [[12, 7, 9], [4, 4, 8, 1]],
        serving.SamplingParams(max_new_tokens=16, seed=1))
    router.drain(0)          # elastic: finish work, respawn warm
    router.shutdown()

See docs/serving.md "Multi-replica routing" for the architecture and
the token-identity / failover contracts.
"""
from paddle_tpu.serving.aot_cache import (AOTProgramCache,
                                          engine_fingerprint)
from paddle_tpu.serving.router.metrics import RouterMetrics
from paddle_tpu.serving.router.replica import ReplicaHandle, ReplicaState
from paddle_tpu.serving.router.router import (Router, RouterConfig,
                                              RouterResult)

__all__ = [
    "AOTProgramCache",
    "ReplicaHandle",
    "ReplicaState",
    "Router",
    "RouterConfig",
    "RouterMetrics",
    "RouterResult",
    "engine_fingerprint",
]
