"""Router — telemetry-driven admission balancing over N engine
replicas.

The multi-engine serving layer ROADMAP item 3 calls "the single biggest
step toward the heavy-traffic north star": a :class:`Router` fronts N
:class:`~paddle_tpu.serving.LLMEngine` replicas and decides WHERE every
request runs from exactly the signals the fleet already exports —
queue depth and page occupancy (PR 8's scrape gauges) and the
hysteretic health state (PR 6) — with no privileged engine
introspection.  Semantics:

- **Telemetry routing.**  Admissions go to the best-scoring admitting
  replica (healthier → emptier queue → lower page occupancy;
  deterministic index tie-break, so identical traces route
  identically).  An engine-DRAINING replica scores itself out of
  rotation before it can reject anything.
- **Sticky affinity.**  A request is owned by one replica for its whole
  decode (continuation batching needs its pages local); the router only
  re-homes it on drain or failure.
- **Spillover + retry.**  An :class:`AdmissionRejected` (queue_full /
  draining) spills the admission to the next-best replica; when EVERY
  replica refuses, :meth:`generate` retries the whole admission under a
  PR 6 :class:`~paddle_tpu.resilience.RetryPolicy` — stepping the fleet
  between attempts, because in-process the productive "backoff" is
  letting the engines drain.
- **Failover without data loss.**  A replica whose ``step()`` raises is
  marked DEAD; every request it owned is migrated through
  ``engine.adopt_request`` — the replay prefill rebuilds the KV cache
  from ``prompt + tokens generated so far`` and the (seed, absolute
  position) sampler regenerates the continuation token-identically, so
  routed output matches the sequential single-engine run even across a
  crash (asserted in tests/test_serving_router.py).
- **Elastic drain/respawn.**  :meth:`drain` takes a replica out of
  rotation (migrating its still-queued work), and an emptied or dead
  replica is respawned through the engine factory — booting WARM from
  the shared AOT program cache (serving/aot_cache.py), which is what
  makes replica churn cheap enough to do on a health signal.

Thread model: one reentrant lock guards all router state; EVERY method
that touches shared state acquires it itself (reentrancy makes the
internal call graph safe), and the optional :meth:`start` background
loop is just another caller of :meth:`step`.  Engines are single-owner
— only the router touches them after construction — so the lock also
serializes engine access.  The lock is held across engine steps
(compute, not blocking IO), but never across replica BOOTS: failover
and drain only queue a respawn, and :meth:`step` runs the engine
factory (XLA compiles, cache file IO, retry backoff sleeps) with the
lock released, so admissions keep flowing while a replica rebuilds.
"""
from __future__ import annotations

import random
import threading
import time
import weakref
from collections import OrderedDict

from paddle_tpu.observability import TraceContext, span, use_context
from paddle_tpu.observability.metrics import next_instance_label
from paddle_tpu.resilience.retry import RetryPolicy, compute_backoff
from paddle_tpu.serving.router.metrics import RouterMetrics
from paddle_tpu.serving.router.replica import ReplicaHandle, ReplicaState
from paddle_tpu.serving.scheduler import AdmissionRejected

__all__ = ["Router", "RouterConfig", "RouterResult"]


class RouterConfig:
    """Fleet policy knobs.

    - `spill_policy` / `boot_policy`: PR 6 :class:`RetryPolicy` objects
      governing, respectively, whole-fleet admission retries in
      :meth:`Router.generate` and replica boot attempts.  Jitter
      defaults to 0 so routed runs replay deterministically; seed the
      policies per host to spread a real fleet.
    - `auto_respawn`: respawn a dead or drained-out replica through the
      engine factory (warm from the AOT cache when one is shared).
    - `warm_boot`: run ``engine.warmup()`` at boot so a replica enters
      rotation with its whole program ladder ready (and the boot time
      measured cold-vs-warm).
    - `stall_rounds`: consecutive event-free step rounds before
      :meth:`Router.generate` declares the fleet wedged instead of
      spinning forever.
    - `sleep`: injectable backoff sleeper (tests pass a no-op).
    """

    def __init__(self, spill_policy=None, boot_policy=None,
                 auto_respawn=True, warm_boot=True, retry_seed=0,
                 finished_retention=1024, stall_rounds=256,
                 sleep=time.sleep):
        self.spill_policy = spill_policy or RetryPolicy(
            max_attempts=6, backoff=0.005, multiplier=2.0, jitter=0.0)
        self.boot_policy = boot_policy or RetryPolicy(
            max_attempts=3, backoff=0.05, multiplier=2.0, jitter=0.0)
        self.auto_respawn = bool(auto_respawn)
        self.warm_boot = bool(warm_boot)
        self.retry_seed = int(retry_seed)
        self.finished_retention = int(finished_retention)
        self.stall_rounds = int(stall_rounds)
        self.sleep = sleep


class RouterResult:
    """What :meth:`Router.generate` returns per prompt."""

    def __init__(self, rec, replica_index):
        self.request_id = rec.rid
        self.prompt_token_ids = list(rec.prompt)
        self.output_token_ids = list(rec.tokens)
        self.finish_reason = rec.finish_reason
        self.migrations = rec.migrations
        self.replica = replica_index

    def __repr__(self):
        return (f"RouterResult({self.request_id}, "
                f"{len(self.output_token_ids)} tokens, "
                f"finish={self.finish_reason}, "
                f"replica={self.replica})")


class _RequestRecord:
    """Router-side shadow of one routed request: everything needed to
    re-home it (prompt, params, tokens so far) without asking the — by
    then possibly dead — owning engine."""

    __slots__ = ("rid", "prompt", "sp", "user_stream", "tokens",
                 "finished", "finish_reason", "replica", "engine_rid",
                 "migrations", "arrive_t", "trace")

    def __init__(self, rid, prompt, sp, user_stream, arrive_t):
        self.rid = rid
        self.prompt = prompt
        self.sp = sp
        self.user_stream = user_stream
        self.tokens = []
        self.finished = False
        self.finish_reason = None
        self.replica = None          # owning ReplicaHandle or None
        self.engine_rid = None
        self.migrations = 0
        self.arrive_t = arrive_t     # router clock; survives migration
        self.trace = None            # TraceContext; survives migration


class Router:
    """N-replica serving router (module docstring has the semantics).

    Construction: either hand it a `model` (+ optional shared
    `engine_config` and `program_cache`) and let it build
    ``LLMEngine``\\ s, or pass ``engine_factory(replica_index) ->
    LLMEngine`` for full control (sharded engines, per-replica
    configs).  The factory is retained for respawns.

    Public surface: :meth:`add_request`, :meth:`step`, :meth:`generate`,
    :meth:`drain`, :meth:`start` / :meth:`stop`, :attr:`metrics`,
    :meth:`snapshot`, :meth:`shutdown`.
    """

    def __init__(self, model=None, engine_config=None, num_replicas=2,
                 config=None, engine_factory=None, program_cache=None,
                 metrics_name=None, clock=None):
        if num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        self.config = config or RouterConfig()
        # injectable timebase: arrive_t stamps, RouterMetrics uptime,
        # and (through the default factory) every engine's
        # EngineMetrics.clock — the virtual-time traffic driver passes
        # one shared VirtualClock so TTFT/deadline accounting is
        # deterministic; None = wall clock, exactly as before
        self._clock = clock if clock is not None else time.perf_counter
        if engine_factory is None:
            if model is None:
                raise ValueError(
                    "pass a model (with optional engine_config) or an "
                    "engine_factory")
            from paddle_tpu.serving.aot_cache import AOTProgramCache
            from paddle_tpu.serving.engine import LLMEngine
            if isinstance(program_cache, str):
                program_cache = AOTProgramCache(program_cache)

            def engine_factory(index):
                return LLMEngine(model, engine_config,
                                 program_cache=program_cache,
                                 clock=clock)

        self._factory = engine_factory
        self._lock = threading.RLock()
        self._stop_event = threading.Event()
        self._thread = None
        self._metrics_name = (metrics_name
                              or next_instance_label("serving.router"))
        self.metrics = RouterMetrics(clock=self._clock,
                                     name=self._metrics_name)
        self._records = {}                 # live rid -> _RequestRecord
        self.finished_results = OrderedDict()    # rid -> RouterResult
        self._by_engine = {}     # (replica, generation, engine_rid) -> rid
        self._pending = []       # rids awaiting (re-)placement
        self._respawns = []      # (index, generation) boots step() owes
        self._reserved = set()   # rids generate() has yet to collect
        self._parked = set()     # replica indices held out of respawn
        self._next_id = 0
        replicas = [self._boot(i, generation=0)
                    for i in range(int(num_replicas))]
        with self._lock:
            self._replicas = replicas
            self.metrics.sync_gauges(live=len(replicas), draining=0)

        from paddle_tpu import profiler
        mref = weakref.ref(self)
        name = self._metrics_name

        def _snapshot():
            r = mref()
            if r is None:
                from paddle_tpu.observability.metrics import registry
                registry().unregister_source(name, expected=_snapshot)
                return {"error": "router collected"}
            return r.snapshot()

        self._snapshot_fn = _snapshot
        profiler.register_metrics_source(name, _snapshot)

    # ------------------------------------------------------------- boot
    def _boot(self, index, generation):
        """Boot one replica (engine factory + warmup), retried under
        `boot_policy`; classifies the boot cold/warm from the engine's
        AOT-cache counters and records it in the boot histograms."""
        policy = self.config.boot_policy
        rng = random.Random(self.config.retry_seed + index)
        attempt = 0
        while True:
            t0 = time.perf_counter()
            try:
                engine = self._factory(index)
                boot = engine.warmup() if self.config.warm_boot else {}
                break
            except Exception as e:
                attempt += 1
                if attempt >= policy.max_attempts:
                    raise
                delay = compute_backoff(policy, attempt - 1, rng)
                with span("serving.router.boot_retry", replica=index,
                          attempt=attempt, exc=type(e).__name__):
                    pass
                if delay > 0:
                    self.config.sleep(delay)
        boot_s = time.perf_counter() - t0
        warm = bool(boot) and boot.get("compiled", 1) == 0 \
            and boot.get("cache_loads", 0) > 0
        with self._lock:
            self.metrics.note_boot(boot_s, warm)
        info = dict(boot)
        info.update(boot_ms=round(boot_s * 1e3, 3), warm=warm)
        with span("serving.router.boot", replica=index,
                  generation=generation, warm=warm,
                  boot_ms=info["boot_ms"]):
            pass
        return ReplicaHandle(index, engine, generation, info)

    def _queue_respawn(self, h):
        """Retire `h`'s engine and owe its slot a fresh boot — executed
        by :meth:`step` OUTSIDE the lock, because a boot is the one
        slow, blocking thing the router does (compiles or cache IO plus
        retry backoff) and holding the lock across it would stall every
        admission in the fleet."""
        with self._lock:
            try:
                h.engine.shutdown()
            except Exception:
                pass
            # a PARKED slot is the autoscaler's spare pool: its drain-
            # out must not auto-respawn — unpark() re-queues the boot
            # when the scale-up policy wants the capacity back
            if self.config.auto_respawn and h.index not in self._parked:
                self._respawns.append((h.index, h.generation + 1))

    def _run_respawns(self):
        """Boot every owed replica with the lock RELEASED, then install
        each under the lock and place any still-pending migrations."""
        while True:
            with self._lock:
                if not self._respawns:
                    return
                index, generation = self._respawns.pop(0)
            try:
                handle = self._boot(index, generation)  # lock released
            except Exception as e:
                # a failed boot (factory bug, transient OOM) must not
                # lose the slot forever: requeue and yield — the next
                # step retries, with the boot policy's backoff inside
                # _boot pacing each round
                with span("serving.router.respawn_failed",
                          replica=index, exc=type(e).__name__):
                    pass
                with self._lock:
                    self._respawns.append((index, generation))
                return
            with self._lock:
                self._replicas[index] = handle
                self.metrics.note_respawn()
            with span("serving.router.respawn", replica=index,
                      generation=generation,
                      warm=handle.boot_info.get("warm", False)):
                pass
            self._retry_pending()

    # -------------------------------------------------------- admission
    def _wrap_stream(self, rec):
        """Every routed request gets a wrapper stream — it is the
        router's ONLY exactly-once token tap.  The engine delivers each
        token exactly once (replays and adoptions skip already-streamed
        prefixes), so appending here keeps `rec.tokens` complete even
        for tokens delivered inside a step() that later RAISED — the
        failover migration then replays the true history and the user
        stream never sees a duplicate."""
        user = rec.user_stream
        rid = rec.rid

        def _stream(req, tok, fin):
            with self._lock:
                if tok is not None:
                    rec.tokens.append(int(tok))
                    self.metrics.generated_tokens += 1
                if fin:
                    # record the finish HERE, not only in the event
                    # path: a request that EOS'd inside a step() that
                    # later raised must never be migrated as unfinished
                    # (the replay would generate past its EOS)
                    rec.finished = True
            if user is not None:
                user(rid, tok, fin)

        return _stream

    def _candidates(self):
        with self._lock:
            return sorted((h for h in self._replicas if h.admitting),
                          key=lambda h: h.score())

    def add_request(self, prompt_token_ids, sampling_params=None,
                    stream=None):
        """Route one request to the best-scoring admitting replica;
        spills to the next on :class:`AdmissionRejected`, raises it only
        when EVERY replica refused.  Returns the router request id
        (``rr-N``).  `stream` receives ``(router_request_id, token,
        finished)`` — already-delivered tokens are never re-streamed
        across a migration."""
        arrive_t = self._clock()  # user callback: never under _lock
        with self._lock:
            self.metrics.requests_received += 1
            candidates = self._candidates()
            if not candidates:
                self.metrics.requests_rejected += 1
                raise AdmissionRejected(
                    "no_replica",
                    "every replica is draining, drained, or dead")
            rid = f"rr-{self._next_id}"
            prompt = [int(t) for t in prompt_token_ids]
            rec = _RequestRecord(rid, prompt, sampling_params, stream,
                                 arrive_t=arrive_t)
            # one distributed trace per request, born at admission: the
            # admit span installs it ambiently, so the engine (local
            # call or KV-RPC wire envelope) records under it
            rec.trace = TraceContext.new(hint=rid)
            last = None
            with span("serving.router.admit", ctx=rec.trace,
                      request=rid, prompt_tokens=len(prompt)):
                for h in candidates:
                    try:
                        erid = h.engine.add_request(
                            prompt, sampling_params,
                            stream=self._wrap_stream(rec))
                    except AdmissionRejected as e:
                        last = e
                        self.metrics.note_spillover()
                        with span("serving.router.spillover",
                                  replica=h.index, reason=e.reason):
                            pass
                        continue
                    rec.replica = h
                    rec.engine_rid = erid
                    self._records[rid] = rec
                    self._by_engine[(h.index, h.generation, erid)] = rid
                    self._next_id += 1
                    self.metrics.requests_routed += 1
                    return rid
            self.metrics.requests_rejected += 1
            raise AdmissionRejected(
                "all_replicas",
                f"{len(candidates)} replicas refused "
                f"(last: {getattr(last, 'reason', '?')})")

    # ------------------------------------------------------------ step
    def step(self):
        """One fleet iteration: place pending migrations, step every
        live replica (failing replicas fail over in-line), recycle
        drained-out replicas.  Returns ``[(router_request_id, token,
        finished), ...]`` across the whole fleet."""
        events = []
        with self._lock:
            self._retry_pending()
            for h in list(self._replicas):
                if not h.alive:
                    continue
                if not h.engine.has_unfinished():
                    if h.state is ReplicaState.DRAINING:
                        self._queue_respawn(h)
                        h.state = ReplicaState.DEAD
                    continue
                try:
                    evs = h.engine.step()
                except Exception as e:
                    self._failover(h, e)
                    continue
                self._absorb_events(h, evs, events)
            self.metrics.sync_gauges(
                live=sum(1 for h in self._replicas if h.alive),
                draining=sum(1 for h in self._replicas
                             if h.state is ReplicaState.DRAINING))
        self._run_respawns()               # boots run OUTSIDE the lock
        return events

    def _absorb_events(self, h, evs, out):
        with self._lock:
            for erid, tok, fin in evs:
                rid = self._by_engine.get((h.index, h.generation, erid))
                if rid is None:
                    continue
                rec = self._records.get(rid)
                if rec is None:
                    continue
                out.append((rid, tok, fin))
                if fin:
                    req = h.engine.finished_requests.pop(erid, None)
                    if req is not None:
                        # authoritative: covers deadline finishes (no
                        # token event) and adopted histories in one shot
                        rec.tokens = [int(t)
                                      for t in req.output_token_ids]
                        rec.finish_reason = req.finish_reason
                    rec.finished = True
                    self._by_engine.pop((h.index, h.generation, erid),
                                        None)
                    self._finish(rec, h.index)

    def _finish(self, rec, replica_index):
        with self._lock:
            self._records.pop(rec.rid, None)
            self.metrics.requests_finished += 1
            self.finished_results[rec.rid] = RouterResult(
                rec, replica_index)
            # retention never evicts a result an in-flight generate()
            # still holds a claim on (`_reserved`) — a burst of
            # finishes larger than the cap must not turn into silent
            # result loss for the caller waiting to collect them
            while len(self.finished_results) > \
                    self.config.finished_retention:
                victim = next((k for k in self.finished_results
                               if k not in self._reserved), None)
                if victim is None:
                    break
                self.finished_results.pop(victim)

    # -------------------------------------------------------- failover
    def _failover(self, h, exc):
        """A replica's step raised: mark it DEAD, migrate every request
        it owned (tokens intact — the adopt replay regenerates the
        continuation token-identically), queue a respawn."""
        with self._lock:
            h.state = ReplicaState.DEAD
            self.metrics.note_failover()
            owned = [rec for rec in self._records.values()
                     if rec.replica is h]
            affected = []
            for rec in owned:
                self._by_engine.pop(
                    (h.index, h.generation, rec.engine_rid), None)
                if rec.finished:
                    # finished inside the crashed step (stream saw its
                    # fin) but the step's events were lost: close it
                    # out from the dead engine's finished table instead
                    # of migrating a done request
                    req = h.engine.finished_requests.pop(
                        rec.engine_rid, None)
                    if req is not None:
                        rec.tokens = [int(t)
                                      for t in req.output_token_ids]
                        rec.finish_reason = req.finish_reason
                    self._finish(rec, h.index)
                    continue
                rec.replica = None
                rec.engine_rid = None
                self._pending.append(rec.rid)
                affected.append(rec)
        with span("serving.router.failover", replica=h.index,
                  exc=type(exc).__name__, requests=len(affected)):
            pass
        self._queue_respawn(h)
        self._retry_pending()

    def _retry_pending(self):
        with self._lock:
            pending, self._pending = self._pending, []
            still = []
            for rid in pending:
                rec = self._records.get(rid)
                if rec is None or rec.finished:
                    continue
                if not self._adopt(rec):
                    still.append(rid)
            self._pending.extend(still)

    def _adopt(self, rec):
        from paddle_tpu.serving.request import SamplingParams
        with self._lock:
            sp = rec.sp
            max_new = (sp if sp is not None
                       else SamplingParams()).max_new_tokens
            if len(rec.tokens) >= max_new:
                # crashed between the last token and its finish event —
                # nothing left to generate; close it out as the engine
                # would
                rec.finished = True
                rec.finish_reason = rec.finish_reason or "length"
                self._finish(rec, -1)
                return True
            for h in self._candidates():
                try:
                    # negative arrival index = "older than every native
                    # admission": a migrated request already paid its
                    # queueing dues, so it must not become the target
                    # engine's preferred (latest-arrived) preemption
                    # victim; router submission order breaks ties.
                    # use_context: the adopting engine's spans (local or
                    # across the wire) rejoin the request's birth trace
                    with use_context(rec.trace):
                        erid = h.engine.adopt_request(
                            rec.prompt, sp,
                            generated_token_ids=rec.tokens,
                            stream=self._wrap_stream(rec),
                            arrive_t=rec.arrive_t,
                            arrival_index=int(rec.rid.split("-")[1])
                            - (1 << 30))
                except (AdmissionRejected, ValueError):
                    continue
                rec.replica = h
                rec.engine_rid = erid
                rec.migrations += 1
                self._by_engine[(h.index, h.generation, erid)] = rec.rid
                self.metrics.adoptions += 1
                return True
            return False

    # ----------------------------------------------------- drain/respawn
    def drain(self, index, migrate_waiting=True):
        """Take replica `index` out of rotation: no new admissions, its
        RUNNING requests finish in place (their pages are local), and —
        with `migrate_waiting` — its still-queued requests are migrated
        to admitting replicas immediately.  Once the replica empties,
        the next :meth:`step` recycles it (respawn under
        `auto_respawn`, else retirement)."""
        with self._lock:
            h = self._replicas[int(index)]
            if not h.alive:
                raise ValueError(f"replica {index} is not alive")
            h.state = ReplicaState.DRAINING
            self.metrics.drains += 1
            with span("serving.router.drain", replica=h.index,
                      migrate_waiting=bool(migrate_waiting)):
                pass
            if migrate_waiting:
                for req in h.engine.release_waiting():
                    rid = self._by_engine.pop(
                        (h.index, h.generation, req.request_id), None)
                    rec = self._records.get(rid) if rid else None
                    if rec is None:
                        continue
                    rec.tokens = [int(t) for t in req.output_token_ids]
                    rec.replica = None
                    rec.engine_rid = None
                    self._pending.append(rec.rid)
        if migrate_waiting:
            self._retry_pending()
        return h

    def park(self, index, migrate_waiting=True):
        """Scale-down: drain replica `index` AND hold its emptied slot
        out of auto-respawn — the slot becomes spare capacity (the
        autoscaler's spare pool) until :meth:`unpark` reclaims it.  A
        normal :meth:`drain` in every other respect: running work
        finishes in place, queued work migrates."""
        with self._lock:
            self._parked.add(int(index))
            return self.drain(index, migrate_waiting)

    def unpark(self, index):
        """Scale-up: reclaim a parked slot through the EXISTING respawn
        queue — the next :meth:`step` boots it outside the lock, warm
        from the shared AOT cache, so admissions never stall behind the
        boot.  A slot still draining is simply returned to rotation
        (the drain is cancelled — cheaper than a boot).  Idempotent on
        non-parked live slots."""
        with self._lock:
            index = int(index)
            self._parked.discard(index)
            h = self._replicas[index]
            if h.state is ReplicaState.DRAINING:
                h.state = ReplicaState.ACTIVE
                with span("serving.router.unpark", replica=index,
                          cancelled_drain=True):
                    pass
                return h
            if not h.alive and \
                    (index, h.generation + 1) not in self._respawns:
                self._respawns.append((index, h.generation + 1))
                with span("serving.router.unpark", replica=index,
                          cancelled_drain=False):
                    pass
            return h

    @property
    def parked(self):
        """Indices currently held out of auto-respawn (spare pool)."""
        with self._lock:
            return set(self._parked)

    # ---------------------------------------------------------- facade
    def has_unfinished(self):
        with self._lock:
            if self._records or self._pending:
                return True
            return any(h.alive and h.engine.has_unfinished()
                       for h in self._replicas)

    def _submit_with_retry(self, prompt, sp):
        """Admission with whole-fleet backpressure retry: every replica
        refusing triggers a fleet step (the productive wait — queues
        drain) plus a `spill_policy` backoff before the next attempt."""
        policy = self.config.spill_policy
        rng = random.Random(self.config.retry_seed)
        attempt = 0
        while True:
            try:
                return self.add_request(prompt, sp)
            except AdmissionRejected:
                attempt += 1
                if attempt >= policy.max_attempts:
                    raise
                with span("serving.router.backpressure",
                          attempt=attempt):
                    pass
                self.step()
                delay = compute_backoff(policy, attempt - 1, rng)
                if delay > 0:
                    self.config.sleep(delay)

    def generate(self, prompts, sampling_params=None):
        """Sync facade: route `prompts` (list of token-id lists) across
        the fleet and serve to completion; returns one
        :class:`RouterResult` per prompt in input order — token-
        identical to a sequential single-engine run regardless of
        routing, drains, or failovers."""
        if prompts and isinstance(prompts[0], int):
            raise TypeError("generate expects a LIST of prompts "
                            "(each a list of token ids)")
        if isinstance(sampling_params, (list, tuple)):
            if len(sampling_params) != len(prompts):
                raise ValueError("one SamplingParams per prompt required")
            sps = list(sampling_params)
        else:
            sps = [sampling_params] * len(prompts)
        rids = []
        try:
            for p, sp in zip(prompts, sps):
                rid = self._submit_with_retry(p, sp)
                rids.append(rid)
                with self._lock:
                    # claim the result: batches larger than
                    # finished_retention must not see their earliest
                    # results evicted before this call collects them
                    self._reserved.add(rid)
            idle = 0
            while True:
                with self._lock:
                    done = all(r in self.finished_results
                               for r in rids)
                if done:
                    break
                if not self.has_unfinished():
                    raise RuntimeError(
                        "router lost track of in-flight requests "
                        "(fleet emptied with results missing)")
                events = self.step()
                idle = 0 if events else idle + 1
                if idle > self.config.stall_rounds:
                    raise RuntimeError(
                        f"router stalled: {self.config.stall_rounds} "
                        f"event-free rounds with requests outstanding "
                        f"(all replicas dead or work unplaceable)")
            with self._lock:
                return [self.finished_results.pop(r) for r in rids]
        finally:
            with self._lock:
                self._reserved.difference_update(rids)

    # --------------------------------------------------- background loop
    def start(self, interval_s=0.005):
        """Spawn the background step loop (daemon thread): admissions
        from any thread are then served without the caller driving
        :meth:`step`.  Idempotent; :meth:`stop` joins it."""
        with self._lock:
            if self._thread is not None:
                return self._thread
            self._stop_event.clear()
            t = threading.Thread(
                target=self._serve_loop, args=(float(interval_s),),
                name=f"{self._metrics_name}.loop", daemon=True)
            self._thread = t
        t.start()
        return t

    def _serve_loop(self, interval_s):
        while not self._stop_event.is_set():
            try:
                events = self.step()
            except Exception as e:
                # the daemon loop must survive a bad step (it is the
                # only thing serving background admissions) — record
                # and pace, don't die silently
                with span("serving.router.loop_error",
                          exc=type(e).__name__):
                    pass
                events = []
            if not events:
                # nothing moved: park on the event (not time.sleep) so
                # stop() wakes the loop immediately
                self._stop_event.wait(interval_s)

    def stop(self):
        """Stop and join the background loop (no-op when not running)."""
        self._stop_event.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30.0)

    # ------------------------------------------------------ observability
    def snapshot(self):
        """Fleet snapshot: router counters + per-replica lifecycle and
        the live telemetry each routing decision reads."""
        with self._lock:
            snap = self.metrics.snapshot()
            snap["replica_detail"] = [h.describe()
                                      for h in self._replicas]
            snap["pending_migrations"] = len(self._pending)
            snap["parked"] = sorted(self._parked)
            return snap

    @property
    def replicas(self):
        with self._lock:
            return list(self._replicas)

    def shutdown(self):
        """Stop the loop, shut every replica down, release the router's
        registry instruments and metrics source."""
        self.stop()
        with self._lock:
            for h in self._replicas:
                try:
                    h.engine.shutdown()
                except Exception:
                    pass
            from paddle_tpu.observability.metrics import registry
            registry().unregister_source(self._metrics_name,
                                         expected=self._snapshot_fn)
            self.metrics.release()
