"""Router observability: fleet-level counters, gauges, and boot-time
histograms over the shared :mod:`paddle_tpu.observability` registry.

Same design as :class:`serving.metrics.EngineMetrics`, one level up:
every instrument is registry-owned under a ``router=<name>`` label
(``router_spillover_total{router=...}`` etc.), so the Prometheus scrape
endpoint, ``profiler.metrics_report()``, and :meth:`snapshot` can never
diverge.  Boot times are split cold/warm — the AOT-program-cache payoff
the bench lane reports as ``router_boot_ms_cold_vs_warm``.
"""
from __future__ import annotations

import time
import weakref

from paddle_tpu.observability.metrics import next_instance_label, registry
from paddle_tpu.serving.metrics import _acquire_labels, _release_labels

__all__ = ["RouterMetrics"]


class RouterMetrics:
    """All router counters in one place; `snapshot()` is the contract."""

    def __init__(self, clock=time.perf_counter, name=None):
        self.clock = clock
        self.started_t = clock()
        reg = registry()
        self.labels = {"router": name or next_instance_label("router")}
        labels = self.labels
        _acquire_labels(labels)
        self._released = False
        self._finalizer = weakref.finalize(
            self, _release_labels, dict(labels))
        # counters (plain attrs mirrored into registry instruments)
        self.requests_received = 0
        self.requests_routed = 0
        self.requests_rejected = 0    # every replica refused
        self.requests_finished = 0
        self.spillovers = 0           # AdmissionRejected → next replica
        self.failovers = 0            # replica crashes handled
        self.adoptions = 0            # requests migrated off a replica
        self.respawns = 0             # replicas re-booted
        self.drains = 0               # router-initiated drains
        self.generated_tokens = 0
        self._spill_counter = reg.counter(
            "router_spillover_total", labels=labels,
            help="admissions spilled to another replica on rejection")
        self._failover_counter = reg.counter(
            "router_failover_total", labels=labels,
            help="replica failures absorbed by migration")
        self._respawn_counter = reg.counter(
            "router_respawn_total", labels=labels,
            help="replica engines re-booted by the router")
        # gauges
        self.replicas_live = 0
        self.replicas_draining = 0
        self.replicas_live_gauge = reg.gauge(
            "router_replicas_live", labels=labels,
            help="replicas accepting or finishing work")
        self.replicas_draining_gauge = reg.gauge(
            "router_replicas_draining", labels=labels,
            help="replicas draining (no new admissions)")
        # histograms (seconds, registry convention)
        self.boot_cold_s = reg.histogram(
            "router_boot_cold_seconds", labels=labels,
            help="replica boot time when programs were compiled")
        self.boot_warm_s = reg.histogram(
            "router_boot_warm_seconds", labels=labels,
            help="replica boot time when programs loaded from AOT cache")

    def release(self):
        """Drop the registry claim (idempotent; last release wins)."""
        if self._released:
            return
        self._released = True
        self._finalizer.detach()
        _release_labels(self.labels)

    def note_spillover(self):
        self.spillovers += 1
        self._spill_counter.inc()

    def note_failover(self):
        self.failovers += 1
        self._failover_counter.inc()

    def note_respawn(self):
        self.respawns += 1
        self._respawn_counter.inc()

    def note_boot(self, seconds, warm):
        (self.boot_warm_s if warm else self.boot_cold_s).observe(seconds)

    def sync_gauges(self, live, draining):
        self.replicas_live = live
        self.replicas_draining = draining
        self.replicas_live_gauge.set(live)
        self.replicas_draining_gauge.set(draining)

    def snapshot(self):
        elapsed = max(self.clock() - self.started_t, 1e-9)
        return {
            "uptime_s": round(elapsed, 3),
            "requests": {
                "received": self.requests_received,
                "routed": self.requests_routed,
                "rejected": self.requests_rejected,
                "finished": self.requests_finished,
            },
            "spillovers": self.spillovers,
            "failovers": self.failovers,
            "adoptions": self.adoptions,
            "respawns": self.respawns,
            "drains": self.drains,
            "replicas": {
                "live": self.replicas_live,
                "draining": self.replicas_draining,
            },
            "tokens": {
                "generated": self.generated_tokens,
                "per_s": round(self.generated_tokens / elapsed, 2),
            },
            "boot_cold_ms": self.boot_cold_s.summary(),
            "boot_warm_ms": self.boot_warm_s.summary(),
        }
