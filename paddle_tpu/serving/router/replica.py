"""One routed engine replica: lifecycle state + the telemetry view the
router balances on.

A :class:`ReplicaHandle` wraps a live :class:`~paddle_tpu.serving.
LLMEngine` with the router-level lifecycle (ACTIVE → DRAINING → DEAD,
plus respawn generations) and exposes exactly the admission signals
PR 8/PR 6 already export — queue depth, page occupancy, the hysteretic
health state — as a deterministic routing score.  The handle never
threads through engine internals: everything it reads is the same
telemetry a remote router would scrape from
``observability.export.serve_prometheus``.
"""
from __future__ import annotations

import enum

__all__ = ["ReplicaState", "ReplicaHandle"]


class ReplicaState(enum.Enum):
    ACTIVE = "active"        # routable
    DRAINING = "draining"    # finishes owned work; no new admissions
    DEAD = "dead"            # crashed or drained-out; awaiting respawn


class ReplicaHandle:
    """Router-side view of one engine replica.

    Mutable state (`state`, `engine`, `generation`) is owned by the
    Router and mutated only under the Router's lock.
    """

    def __init__(self, index, engine, generation=0, boot_info=None):
        self.index = int(index)
        self.engine = engine
        self.state = ReplicaState.ACTIVE
        self.generation = int(generation)   # bumped per respawn
        self.boot_info = dict(boot_info or {})

    # ------------------------------------------------------- telemetry
    @property
    def alive(self):
        return self.state is not ReplicaState.DEAD

    @property
    def admitting(self):
        """Routable right now (router-level lifecycle only).  An
        engine-health-DRAINING replica stays a candidate: its health
        score already sorts it last, and if it IS tried its engine
        answers with the machine-readable ``AdmissionRejected`` the
        router's spillover path consumes — the backpressure contract,
        not a silent filter."""
        return self.state is ReplicaState.ACTIVE

    def telemetry(self):
        """The admission signals — the same quantities the
        ``serving_queue_depth`` / ``serving_page_occupancy`` scrape
        gauges export, read live at the source so burst admissions
        between step boundaries see each other land."""
        e = self.engine
        return {
            "health": int(e.health.state),
            "queue_depth": int(e.queue_depth),
            "page_occupancy": round(float(e.page_occupancy), 4),
            "running": int(e.num_running),
        }

    def score(self):
        """Deterministic routing preference: healthier, emptier-queued,
        lower-occupancy replicas first; replica index breaks ties so
        two identical runs route identically."""
        t = self.telemetry()
        return (t["health"], t["queue_depth"], t["page_occupancy"],
                t["running"], self.index)

    def describe(self):
        d = {"index": self.index, "state": self.state.value,
             "generation": self.generation}
        d.update(self.telemetry())
        if self.boot_info:
            d["boot"] = dict(self.boot_info)
        return d

    def __repr__(self):
        return (f"ReplicaHandle({self.index}, {self.state.value}, "
                f"gen={self.generation})")
