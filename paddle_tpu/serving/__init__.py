"""paddle_tpu.serving — continuous-batching LLM serving engine.

TPU-native serving (Ragged Paged Attention + the Gemma-on-TPU serving
recipe, PAPERS.md): a paged KV cache shared by every in-flight request,
continuous batching at decode-step boundaries, prompt-length bucketing
to a CLOSED set of compiled shapes (the engine's whole lifetime compiles
``len(buckets) + 3`` XLA programs, asserted at runtime), and traced
per-request sampling whose draws depend only on (seed, token position) —
so continuous batching, sequential decode, and preemption replay all
produce identical tokens.

Quickstart::

    from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
    from paddle_tpu import serving

    engine = serving.LLMEngine(GPTForCausalLM(gpt3_tiny()),
                               serving.EngineConfig(max_num_seqs=8,
                                                    max_model_len=128))
    results = engine.generate(
        [[12, 7, 9], [4, 4, 8, 1]],
        serving.SamplingParams(max_new_tokens=16, temperature=0.8,
                               top_p=0.95, seed=1))

Multi-replica serving lives one level up: :mod:`paddle_tpu.serving.
router` fronts N engines with telemetry-driven admission balancing,
failover, and elastic drain/respawn, with replicas booting warm from
the persisted AOT program cache (:mod:`paddle_tpu.serving.aot_cache`).
:mod:`paddle_tpu.serving.traffic` is the measurement harness over it
all: deterministic workload-model load generation, an SLO autoscaler,
and binary-search capacity reports (max sustained QPS at a TTFT SLO).

See docs/serving.md for the architecture and the request lifecycle.
"""
from paddle_tpu.serving import fleet, router, traffic
from paddle_tpu.serving.aot_cache import (AOTProgramCache,
                                          engine_fingerprint)
from paddle_tpu.serving.engine import (EngineConfig, LLMEngine,
                                       PagedKVContext)
from paddle_tpu.serving.metrics import EngineMetrics, Histogram
from paddle_tpu.serving.request import (GenerationResult, Request,
                                        RequestState, SamplingParams)
from paddle_tpu.serving.sampler import sample_tokens
from paddle_tpu.serving.scheduler import (AdmissionRejected, Scheduler,
                                          bucket_for, default_buckets)

__all__ = [
    "AOTProgramCache",
    "AdmissionRejected",
    "EngineConfig",
    "EngineMetrics",
    "GenerationResult",
    "Histogram",
    "LLMEngine",
    "PagedKVContext",
    "Request",
    "RequestState",
    "SamplingParams",
    "Scheduler",
    "bucket_for",
    "default_buckets",
    "engine_fingerprint",
    "fleet",
    "router",
    "sample_tokens",
    "traffic",
]
