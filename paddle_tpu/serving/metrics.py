"""Serving observability: counters, gauges, and latency histograms,
exposed as one plain-dict snapshot.

Backing store: the process-wide :mod:`paddle_tpu.observability`
registry.  ``Histogram`` here IS ``observability.metrics.Histogram``
(compatibility alias), every latency histogram is registered under an
engine-labeled Prometheus name (``serving_ttft_seconds{engine=...}``),
and ``note_compile`` bumps the registry's ``serving_compile_total``
counter — so `profiler.metrics_report()` and the Prometheus exporter
both see engine compile counts / TTFT / ITL directly, not through a
diverging side-registry.  The `snapshot()` dict remains the stable
coarse integration surface (`LLMEngine` registers it as a metrics
source; see docs/serving.md 'Metrics reference').
"""
from __future__ import annotations

import threading
import time
import weakref

from paddle_tpu.observability.metrics import (Histogram, _label_key,
                                              next_instance_label,
                                              registry)

__all__ = ["Histogram", "EngineMetrics"]

# Live-instance count per label set.  Two engines created with the same
# explicit `metrics_name` SHARE registry instruments (same (name,
# labels) key — Prometheus semantics), so the instruments may only be
# dropped when the LAST owner releases; otherwise one engine's
# shutdown() would silently delete a live engine's histograms from the
# registry while its snapshot() kept reporting them — exactly the
# snapshot-vs-Prometheus divergence this layer exists to rule out.
_live_labels = {}
_live_lock = threading.Lock()


def _acquire_labels(labels):
    key = _label_key(labels)
    with _live_lock:
        _live_labels[key] = _live_labels.get(key, 0) + 1


def _release_labels(labels):
    key = _label_key(labels)
    # Drop while still holding _live_lock: deciding n==0 and then
    # dropping outside the lock would let a same-named engine created
    # in the gap lose its freshly re-created instruments.
    with _live_lock:
        n = _live_labels.get(key, 0) - 1
        if n > 0:
            _live_labels[key] = n
            return
        _live_labels.pop(key, None)
        if n == 0:
            registry().drop_labeled(labels)


class EngineMetrics:
    """All engine counters in one place; `snapshot()` is the contract.

    `name` labels this instance's registry instruments; an unnamed
    instance (tests, ad-hoc use) gets a unique generated label so two
    engines never share a histogram by accident."""

    def __init__(self, clock=time.perf_counter, name=None):
        self.clock = clock
        self.started_t = clock()
        reg = registry()
        self.labels = {"engine": name or next_instance_label("engine")}
        labels = self.labels
        _acquire_labels(labels)
        self._released = False
        # GC safety net: an instance dropped without release() must
        # still decrement the live count, or the labels leak forever
        self._finalizer = weakref.finalize(
            self, _release_labels, dict(labels))
        # counters
        self.requests_received = 0
        self.requests_admitted = 0
        self.requests_finished = 0
        self.requests_evicted = 0
        self.requests_rejected = 0   # backpressure (queue_full/draining)
        self.requests_expired = 0    # deadline enforcement
        self.requests_adopted = 0    # router failover migrations in
        self.decode_fault_recoveries = 0
        self.guard_anomalies = 0     # sentinel guard-flagged requests
        self.prefill_steps = 0
        self.decode_steps = 0
        self.prompt_tokens = 0
        self.generated_tokens = 0
        self.compile_count = 0
        self.compile_bound = 0
        self.aot_cache_loads = 0     # warm-boot program-cache hits
        self._compile_counter = reg.counter(
            "serving_compile_total", labels=labels,
            help="XLA programs compiled by the serving engine")
        self._aot_load_counter = reg.counter(
            "serving_aot_load_total", labels=labels,
            help="programs loaded from the AOT cache instead of compiled")
        # gauges (engine pushes current values)
        self.queue_depth = 0
        self.running = 0
        self.pages_in_use = 0
        self.pages_total = 0
        self.health = "healthy"      # engine-pushed health-state name
        self.health_state = reg.gauge(
            "serving_health_state", labels=labels,
            help="engine health: 0 healthy / 1 degraded / 2 draining")
        # live admission signals for the multi-engine router's scrape
        # path (observability.export.serve_prometheus): refreshed from
        # the plain attrs above by sync_gauges() at every engine step
        self.queue_depth_gauge = reg.gauge(
            "serving_queue_depth", labels=labels,
            help="requests waiting for admission")
        self.page_occupancy_gauge = reg.gauge(
            "serving_page_occupancy", labels=labels,
            help="KV page-pool occupancy fraction (0..1)")
        # histograms (seconds) — registry-owned, engine-labeled
        self.ttft = reg.histogram(
            "serving_ttft_seconds", labels=labels,
            help="time to first token")
        # TTFT stage decomposition (fleettrace): for a fresh request
        # TTFT = queue + prefill exactly; decode is the resume latency
        # of migrated/adopted work (time from adoption on THIS engine
        # to the first token it produces) and is absent otherwise
        self.ttft_queue = reg.histogram(
            "serving_ttft_queue_seconds", labels=labels,
            help="TTFT stage: arrival to prefill start (queue wait)")
        self.ttft_prefill = reg.histogram(
            "serving_ttft_prefill_seconds", labels=labels,
            help="TTFT stage: prefill start to first token")
        self.ttft_decode = reg.histogram(
            "serving_ttft_decode_seconds", labels=labels,
            help="TTFT stage: adoption/import to first resumed token")
        self.inter_token = reg.histogram(
            "serving_inter_token_seconds", labels=labels,
            help="inter-token latency")
        self.e2e_latency = reg.histogram(
            "serving_e2e_latency_seconds", labels=labels,
            help="request end-to-end latency")
        self.prefill_step_s = reg.histogram(
            "serving_prefill_step_seconds", labels=labels,
            help="prefill step wall time")
        self.decode_step_s = reg.histogram(
            "serving_decode_step_seconds", labels=labels,
            help="decode step wall time")

    def release(self):
        """Release this instance's claim on its registry instruments —
        a finite-lifetime engine must not grow the registry forever.
        The instruments are dropped only when the last same-labeled
        instance releases (idempotent)."""
        if self._released:
            return
        self._released = True
        self._finalizer.detach()
        _release_labels(self.labels)

    def sync_gauges(self):
        """Mirror the engine-pushed plain attrs into their registry
        gauges, so the Prometheus scrape and snapshot() can't diverge
        (same invariant the histograms get by being registry-owned)."""
        self.queue_depth_gauge.set(self.queue_depth)
        self.page_occupancy_gauge.set(
            self.pages_in_use / self.pages_total if self.pages_total
            else 0.0)

    def note_aot_load(self):
        """One program loaded from the persisted AOT cache — NOT a
        compile: deliberately outside `note_compile` and the recompile
        log, so a warm boot's compile count stays zero."""
        self.aot_cache_loads += 1
        self._aot_load_counter.inc()

    def note_compile(self):
        self.compile_count += 1
        self._compile_counter.inc()
        if self.compile_bound and self.compile_count > self.compile_bound:
            raise RuntimeError(
                f"recompile storm: {self.compile_count} compiles exceeds "
                f"the declared bound {self.compile_bound} — a shape "
                f"escaped the bucket set")

    def snapshot(self):
        """Plain-dict view of everything (stable keys; see
        docs/serving.md 'Metrics reference')."""
        elapsed = max(self.clock() - self.started_t, 1e-9)
        return {
            "uptime_s": round(elapsed, 3),
            "requests": {
                "received": self.requests_received,
                "admitted": self.requests_admitted,
                "finished": self.requests_finished,
                "evicted": self.requests_evicted,
                "rejected": self.requests_rejected,
                "expired": self.requests_expired,
                "adopted": self.requests_adopted,
            },
            "queue_depth": self.queue_depth,
            "running": self.running,
            "health": self.health,
            "decode_fault_recoveries": self.decode_fault_recoveries,
            "guard_anomalies": self.guard_anomalies,
            "steps": {
                "prefill": self.prefill_steps,
                "decode": self.decode_steps,
            },
            "tokens": {
                "prompt": self.prompt_tokens,
                "generated": self.generated_tokens,
                "per_s": round(self.generated_tokens / elapsed, 2),
            },
            "pages": {
                "in_use": self.pages_in_use,
                "total": self.pages_total,
                "utilization": round(
                    self.pages_in_use / self.pages_total, 4)
                if self.pages_total else 0.0,
            },
            "compiles": {
                "count": self.compile_count,
                "bound": self.compile_bound,
                "cache_loads": self.aot_cache_loads,
            },
            "ttft_ms": self.ttft.summary(),
            "inter_token_ms": self.inter_token.summary(),
            "e2e_latency_ms": self.e2e_latency.summary(),
            "prefill_step_ms": self.prefill_step_s.summary(),
            "decode_step_ms": self.decode_step_s.summary(),
        }
