"""Serving observability: counters, gauges, and latency histograms,
exposed as one plain-dict snapshot.

The snapshot is the integration surface: `LLMEngine` registers its
`snapshot` with `paddle_tpu.profiler.register_metrics_source`, so a
profiler report over a serving process includes queue depth, tokens/s,
TTFT, inter-token latency percentiles, page utilization, and — the
recompile-storm tripwire — the compile counter next to its declared
bound.
"""
from __future__ import annotations

import time
from collections import deque

__all__ = ["Histogram", "EngineMetrics"]


class Histogram:
    """Bounded-memory latency histogram: keeps the most recent `cap`
    observations (seconds) and summarizes on demand.  `observe` is in
    the per-token hot path, so eviction must be O(1) (deque maxlen)."""

    def __init__(self, cap=4096):
        self.cap = int(cap)
        self._vals = deque(maxlen=self.cap)
        self.count = 0

    def observe(self, v):
        self.count += 1
        self._vals.append(float(v))

    def _percentile(self, q):
        vs = sorted(self._vals)
        if not vs:
            return None
        idx = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
        return vs[idx]

    def summary(self, scale=1000.0):
        """{count, mean, p50, p99} — scaled (default: seconds -> ms)."""
        if not self._vals:
            return {"count": self.count, "mean": None, "p50": None,
                    "p99": None}
        mean = sum(self._vals) / len(self._vals)
        return {
            "count": self.count,
            "mean": round(mean * scale, 4),
            "p50": round(self._percentile(0.50) * scale, 4),
            "p99": round(self._percentile(0.99) * scale, 4),
        }


class EngineMetrics:
    """All engine counters in one place; `snapshot()` is the contract."""

    def __init__(self, clock=time.perf_counter):
        self.clock = clock
        self.started_t = clock()
        # counters
        self.requests_received = 0
        self.requests_admitted = 0
        self.requests_finished = 0
        self.requests_evicted = 0
        self.prefill_steps = 0
        self.decode_steps = 0
        self.prompt_tokens = 0
        self.generated_tokens = 0
        self.compile_count = 0
        self.compile_bound = 0
        # gauges (engine pushes current values)
        self.queue_depth = 0
        self.running = 0
        self.pages_in_use = 0
        self.pages_total = 0
        # histograms (seconds)
        self.ttft = Histogram()
        self.inter_token = Histogram()
        self.e2e_latency = Histogram()
        self.prefill_step_s = Histogram()
        self.decode_step_s = Histogram()

    def note_compile(self):
        self.compile_count += 1
        if self.compile_bound and self.compile_count > self.compile_bound:
            raise RuntimeError(
                f"recompile storm: {self.compile_count} compiles exceeds "
                f"the declared bound {self.compile_bound} — a shape "
                f"escaped the bucket set")

    def snapshot(self):
        """Plain-dict view of everything (stable keys; see
        docs/serving.md 'Metrics reference')."""
        elapsed = max(self.clock() - self.started_t, 1e-9)
        return {
            "uptime_s": round(elapsed, 3),
            "requests": {
                "received": self.requests_received,
                "admitted": self.requests_admitted,
                "finished": self.requests_finished,
                "evicted": self.requests_evicted,
            },
            "queue_depth": self.queue_depth,
            "running": self.running,
            "steps": {
                "prefill": self.prefill_steps,
                "decode": self.decode_steps,
            },
            "tokens": {
                "prompt": self.prompt_tokens,
                "generated": self.generated_tokens,
                "per_s": round(self.generated_tokens / elapsed, 2),
            },
            "pages": {
                "in_use": self.pages_in_use,
                "total": self.pages_total,
                "utilization": round(
                    self.pages_in_use / self.pages_total, 4)
                if self.pages_total else 0.0,
            },
            "compiles": {
                "count": self.compile_count,
                "bound": self.compile_bound,
            },
            "ttft_ms": self.ttft.summary(),
            "inter_token_ms": self.inter_token.summary(),
            "e2e_latency_ms": self.e2e_latency.summary(),
            "prefill_step_ms": self.prefill_step_s.summary(),
            "decode_step_ms": self.decode_step_s.summary(),
        }
