"""LLMEngine — continuous-batching serving over a paged KV cache.

Execution model (the Gemma-on-TPU serving recipe, PAPERS.md): a SMALL,
FIXED set of compiled programs serves every request mix —

- one PREFILL program per prompt-length bucket: ``[1, bucket]`` token
  ids in, dense causal attention, KV scattered into the shared paged
  pools, last-real-token logits out;
- ONE DECODE program at the full slot width ``[B, 1]``: every live
  sequence appends its token at its own length and attends over its own
  pages (ragged continuation batching — no re-padding, ever);
- one SAMPLER program per width (prefill=1, decode=B) with every knob
  (temperature/top-k/top-p/seed) as a traced operand.

Compile count is therefore bounded by ``len(buckets) + 3`` for the life
of the engine; `EngineMetrics.note_compile` hard-fails past the bound
(the recompile storm tracelint TL3xx polices, turned into a runtime
assertion).

Continuous batching: new requests join the running decode batch at step
boundaries (admission → bucketed prefill → slot in the decode batch),
finished sequences free their pages immediately, and when the pool runs
dry the latest-arrived running request is deterministically preempted
(recompute-style: replayed later by prefilling prompt + generated
tokens; positional sampling seeds make the replay token-identical —
bit-exact on CPU; on TPU a replayed position is computed by the prefill
program instead of the decode program, so a near-tie in bf16 logits
could in principle resolve differently across an eviction).

Everything host-side here is orchestration over device arrays; the only
jax entry points are the compiled step programs, so the engine runs
bit-deterministically on the CPU mesh (``JAX_PLATFORMS=cpu``) and
unchanged on TPU.
"""
from __future__ import annotations

import time
import weakref
from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.autograd import no_grad
from paddle_tpu.observability import (TraceContext, current_context,
                                      note_aot_compile, span)
from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.incubate.nn.paged_attention import (PageAllocator,
                                                    paged_decode_step,
                                                    paged_prefill_append)
from paddle_tpu.quantization.kv_cache import (quantized_decode_step,
                                              quantized_prefill_append,
                                              resolve_kv_cache_dtype)
from paddle_tpu.resilience.faultinject import fire as _fire
from paddle_tpu.resilience.faultinject import note_recovery
from paddle_tpu.resilience.health import HealthMonitor
from paddle_tpu.serving.metrics import EngineMetrics
from paddle_tpu.serving.request import (GenerationResult, Request,
                                        RequestState, SamplingParams)
from paddle_tpu.serving.sampler import sample_tokens
from paddle_tpu.serving.scheduler import (AdmissionRejected, Scheduler,
                                          default_buckets)

__all__ = ["AdmissionRejected", "EngineConfig", "LLMEngine",
           "PagedKVContext"]


class EngineConfig:
    """Sizing and shape-bucketing knobs for :class:`LLMEngine`.

    - `max_num_seqs`: decode batch width B (slots).
    - `page_size` / `num_pages`: shared-pool geometry.  The default pool
      holds every slot at `max_model_len` (no preemption pressure);
      size it DOWN to oversubscribe memory and exercise preemption.
    - `prefill_buckets`: the closed set of padded prompt shapes; the
      engine never compiles any other prefill width.
    - `eos_token_id`: default stop token for requests that don't set one.
    - `max_queue_depth`: bounded admission — `add_request` past this
      waiting-queue depth raises :class:`AdmissionRejected` (explicit
      backpressure) instead of queueing unboundedly.
    - `crash_safe_decode`: a decode-step exception evicts-and-requeues
      the offending request (replayed token-identically) instead of
      killing the engine.
    - `health_*`: thresholds for the HEALTHY→DEGRADED→DRAINING state
      machine driven by live page-pool occupancy; DRAINING rejects new
      admissions until pressure falls.
    - `mesh`: tp-sharding groundwork — a ``jax.sharding.Mesh`` (or a
      ``{"tp": n}`` dict resolved over the first n devices) over which
      the engine shards the per-layer paged KV pools along the HEAD
      axis and the weights along their trailing hidden-multiple axis;
      every program lowers as one SPMD computation over the mesh.
      `num_heads` must divide by the tp extent.
    - `kv_cache_dtype`: None (pools stored at `dtype`) or a
      quantization code dtype ("int8", and "fp8_e4m3"/"fp8_e5m2" where
      this jax has the dtype) — the per-layer pools become
      per-page-scaled ``(codes, scales)`` pairs
      (paddle_tpu/quantization/kv_cache.py; docs/quantization.md has
      the storage format and the tolerance contract).  Activations and
      logits stay at `dtype`; only KV storage narrows.
    - `guard`: the serving half of the training sentinel
      (docs/resilience.md "Numerics sentinel") — the decode program
      additionally returns a per-slot anomaly flag pair (non-finite
      logits row; quantized-KV page-scale overflow) computed in-trace,
      and a flagged request is evicted-and-requeued through the
      crash-safe-decode path instead of poisoning the shared pools.
      After ``guard_requeue_limit`` guard evictions the request
      finishes with ``finish_reason="anomaly"`` (a deterministic
      poison would otherwise replay forever).  ``guard_scale_limit``
      additionally bounds quantized page scales (None = finite-only).
    """

    def __init__(self, max_num_seqs=8, page_size=16, max_model_len=256,
                 num_pages=None, prefill_buckets=None,
                 growth_reserve_pages=1, eos_token_id=None,
                 dtype=jnp.float32, finished_retention=1024,
                 max_queue_depth=None, crash_safe_decode=True,
                 health_degraded_at=0.85, health_drain_at=0.97,
                 health_recover_at=0.70, mesh=None, kv_cache_dtype=None,
                 guard=False, guard_scale_limit=None,
                 guard_requeue_limit=2):
        if max_num_seqs < 1:
            raise ValueError("max_num_seqs must be >= 1")
        self.max_num_seqs = int(max_num_seqs)
        self.page_size = int(page_size)
        self.max_model_len = int(max_model_len)
        self.max_pages_per_seq = -(-self.max_model_len // self.page_size)
        if num_pages is None:
            num_pages = self.max_num_seqs * self.max_pages_per_seq + 1
        self.num_pages = int(num_pages)
        if prefill_buckets is None:
            prefill_buckets = default_buckets(self.max_model_len)
        buckets = tuple(sorted(int(b) for b in prefill_buckets))
        if not buckets or buckets[-1] > self.max_model_len:
            raise ValueError(
                f"prefill_buckets {buckets} must be non-empty and "
                f"<= max_model_len {self.max_model_len}")
        self.prefill_buckets = buckets
        self.growth_reserve_pages = int(growth_reserve_pages)
        self.eos_token_id = eos_token_id
        self.dtype = dtype
        # finished Request objects kept for post-hoc inspection via
        # `engine.finished_requests`; oldest are dropped past this cap
        # so a long-running step() loop cannot grow without bound
        self.finished_retention = int(finished_retention)
        self.max_queue_depth = (int(max_queue_depth)
                                if max_queue_depth is not None else None)
        self.crash_safe_decode = bool(crash_safe_decode)
        self.health_degraded_at = float(health_degraded_at)
        self.health_drain_at = float(health_drain_at)
        self.health_recover_at = float(health_recover_at)
        self.mesh = mesh                 # Mesh | {"tp": n} | None
        # resolve eagerly so a typo'd dtype fails at config build, not
        # first step; the spec itself is re-derived by the engine
        self.kv_cache_dtype = (None if kv_cache_dtype is None
                               else resolve_kv_cache_dtype(
                                   kv_cache_dtype).name)
        self.guard = bool(guard)
        self.guard_scale_limit = (float(guard_scale_limit)
                                  if guard_scale_limit is not None
                                  else None)
        self.guard_requeue_limit = int(guard_requeue_limit)

    @property
    def compile_bound(self):
        """Declared ceiling on XLA compiles for the engine's lifetime:
        one prefill per bucket + one decode + two sampler widths."""
        return len(self.prefill_buckets) + 3


class PagedKVContext:
    """The cache-aware attention hook handed to ``model(..., kv_ctx=)``.

    Lives only INSIDE a traced step function: it carries the traced
    per-layer pool arrays and a layer cursor; each attention layer calls
    :meth:`attend` exactly once per forward.

    - mode "prefill": dense causal attention over the (padded) prompt —
      the padded tail only pollutes its own discarded rows — plus a
      batched scatter of the real tokens' K/V into the pages.
    - mode "decode": one-token append + attention over the row's pages
      at its own length (ragged).

    `quant` (a :class:`~paddle_tpu.quantization.kv_cache.KVQuantSpec`,
    None for plain pools) switches the pool entries to per-page-scaled
    ``(codes, scales)`` pairs and routes writes/reads through the
    quantized step functions — decode dequantizes in-trace with f32
    score/value accumulation.
    """

    def __init__(self, k_pools, v_pools, tables, lens, page_size, mode,
                 quant=None):
        self.k_pools = list(k_pools)
        self.v_pools = list(v_pools)
        self.tables = tables
        self.lens = lens
        self.page_size = page_size
        self.mode = mode
        self.quant = quant
        self._layer = 0

    def attend(self, q, k, v):
        """q/k/v: Tensor [b, s, n_head, head_dim] -> Tensor same shape
        (attention output); writes this layer's K/V into its pools."""
        li = self._layer
        self._layer += 1
        if li >= len(self.k_pools):
            raise RuntimeError(
                f"model has more attention layers ({li + 1}+) than the "
                f"engine allocated pools for ({len(self.k_pools)})")

        def fn(qv, kv, vv):
            qT = jnp.swapaxes(qv, 1, 2)            # [b, h, s, d]
            kT = jnp.swapaxes(kv, 1, 2)
            vT = jnp.swapaxes(vv, 1, 2)
            if self.mode == "prefill":
                out = _dense_causal_attention(qT, kT, vT)
                if self.quant is not None:
                    kp, vp = quantized_prefill_append(
                        kT, vT, self.k_pools[li], self.v_pools[li],
                        self.tables, self.lens, self.page_size,
                        self.quant)
                else:
                    kp, vp = paged_prefill_append(
                        kT, vT, self.k_pools[li], self.v_pools[li],
                        self.tables, self.lens, self.page_size)
            elif self.quant is not None:
                out, kp, vp = quantized_decode_step(
                    qT, kT, vT, self.k_pools[li], self.v_pools[li],
                    self.tables, self.lens, self.page_size, self.quant)
            else:
                out, kp, vp = paged_decode_step(
                    qT, kT, vT, self.k_pools[li], self.v_pools[li],
                    self.tables, self.lens, self.page_size)
            self.k_pools[li] = kp
            self.v_pools[li] = vp
            return jnp.swapaxes(out, 1, 2)         # [b, s, h, d]

        return apply(fn, q, k, v)


def _dense_causal_attention(q, k, v):
    """[b, h, s, d] causal attention (fp32 softmax, deterministic).

    Narrow (bf16/fp16) inputs accumulate both contractions wide and
    round once at the output (numlint NL101); the f32 path — today's
    every serving config — is byte-identical to the pre-fix jaxpr.
    """
    d = q.shape[-1]
    s = q.shape[2]
    narrow = q.dtype in (jnp.bfloat16, jnp.float16)
    pet = {"preferred_element_type": jnp.float32} if narrow else {}
    scores = jnp.matmul(q / jnp.sqrt(jnp.float32(d)).astype(q.dtype),
                        jnp.swapaxes(k, -1, -2), **pet)  # [b, h, s, s]
    causal = jnp.tril(jnp.ones((s, s), jnp.bool_))
    scores = jnp.where(causal[None, None], scores.astype(jnp.float32),
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.matmul(probs, v, **pet).astype(q.dtype)


class LLMEngine:
    """Continuous-batching engine over any kv_ctx-aware decoder model.

    The model contract (`models/gpt.py` is the reference attach point):

    - ``model.config`` exposes ``num_layers``, ``num_heads``,
      ``hidden_size`` (head_dim = hidden_size // num_heads);
    - ``model(input_ids, position_ids=..., kv_ctx=...)`` returns
      ``[b, s, vocab]`` logits, with every attention layer delegating to
      ``kv_ctx.attend(q, k, v)`` when a context is passed.

    Public surface: :meth:`add_request`, :meth:`step`, :meth:`generate`,
    :attr:`metrics`, :meth:`shutdown`.
    """

    def __init__(self, model, config=None, metrics_name=None,
                 program_cache=None, clock=None):
        self.config = config or EngineConfig()
        cfg = self.config
        self._model = model
        model.eval()
        mc = model.config
        self._num_layers = int(mc.num_layers)
        self._num_heads = int(mc.num_heads)
        self._head_dim = int(mc.hidden_size) // int(mc.num_heads)
        if cfg.max_model_len > int(getattr(mc, "max_seq_len",
                                           cfg.max_model_len)):
            raise ValueError(
                f"max_model_len {cfg.max_model_len} exceeds the model's "
                f"max_seq_len {mc.max_seq_len}")

        self._params = {k: t._value for k, t in model.state_dict().items()}
        self._init_mesh(cfg.mesh)
        if self._mesh is not None:
            self._params = {k: jax.device_put(
                v, self._param_sharding(v))
                for k, v in self._params.items()}

        B, P = cfg.max_num_seqs, cfg.max_pages_per_seq
        pool_shape = (cfg.num_pages, self._num_heads, cfg.page_size,
                      self._head_dim)
        # kv_cache_dtype narrows the pool STORAGE only: quantized pools
        # are (codes, scales) pairs with one f32 scale per (page, head)
        self._kv_quant = resolve_kv_cache_dtype(cfg.kv_cache_dtype)

        def _pool():
            if self._kv_quant is None:
                return self._place(jnp.zeros(pool_shape, cfg.dtype),
                                   self._pool_sharding)
            return (self._place(jnp.zeros(pool_shape,
                                          self._kv_quant.code_dtype),
                                self._pool_sharding),
                    self._place(jnp.zeros(pool_shape[:2], jnp.float32),
                                self._pool_sharding))

        self._k_pools = [_pool() for _ in range(self._num_layers)]
        self._v_pools = [_pool() for _ in range(self._num_layers)]
        self._tables = np.zeros((B, P), np.int32)      # host-canonical
        self._lens = np.zeros((B,), np.int32)          # host-canonical
        self._alloc = PageAllocator(cfg.num_pages, B, P)
        self._slots = [None] * B                       # Request | None

        self.scheduler = Scheduler(cfg.prefill_buckets, cfg.page_size,
                                   cfg.growth_reserve_pages,
                                   max_queue_depth=cfg.max_queue_depth)
        from paddle_tpu.observability.metrics import next_instance_label
        # a monotonic default label, never id()-derived: a reused id
        # after GC would silently merge this engine's registry metrics
        # into a dead engine's accumulated totals
        self._metrics_name = (metrics_name
                              or next_instance_label("serving.engine"))
        # the engine's histograms/compile counter live in the shared
        # observability registry under this engine's label — the
        # snapshot-source registration below is the coarse view of the
        # SAME instruments, so the two can never diverge
        # `clock` injects the engine's whole timebase (arrive_t stamps,
        # deadline TTLs, TTFT/ITL histograms — everything reads
        # metrics.clock): the virtual-time traffic driver passes a
        # VirtualClock so latency accounting is deterministic; None =
        # wall clock, exactly as before
        self.metrics = (EngineMetrics(clock=clock,
                                      name=self._metrics_name)
                        if clock is not None
                        else EngineMetrics(name=self._metrics_name))
        self.metrics.compile_bound = cfg.compile_bound
        self.metrics.pages_total = cfg.num_pages - 1   # page 0 reserved
        # health state machine over live page-pool occupancy; the gauge
        # is EngineMetrics-owned so its registry lifecycle matches
        self.health = HealthMonitor(
            degraded_at=cfg.health_degraded_at,
            drain_at=cfg.health_drain_at,
            recover_at=cfg.health_recover_at,
            gauge=self.metrics.health_state)
        self._decode_fault_streak = 0

        # AOT program cache (serving/aot_cache.py): a warm boot loads
        # every program this engine would compile instead of compiling
        # it — the whole-program-compilation-as-deployment-artifact
        # model.  A str is a cache directory; None disables.
        if isinstance(program_cache, str):
            from paddle_tpu.serving.aot_cache import AOTProgramCache
            program_cache = AOTProgramCache(program_cache)
        self._program_cache = program_cache
        self._program_fp = None
        if program_cache is not None:
            from paddle_tpu.serving.aot_cache import engine_fingerprint
            self._program_fp = engine_fingerprint(
                mc, cfg, self._params, self._mesh)

        self._compiled = {}
        self._requests = {}          # live (queued or running) only
        # finished requests move here (bounded by finished_retention);
        # generate() drains its own, step()-loop users may inspect/pop
        self.finished_requests = OrderedDict()
        self._next_id = 0

        from paddle_tpu import profiler
        # weak registration: a dropped engine (no shutdown()) must stay
        # collectable and self-evict from the registry on the next report
        mref = weakref.ref(self.metrics)
        name = self._metrics_name

        def _snapshot():
            m = mref()
            if m is None:
                # instruments are released by the EngineMetrics GC
                # finalizer; here only the source entry is evicted —
                # and only if it is still OURS (a newer engine may have
                # re-registered the same name)
                from paddle_tpu.observability.metrics import registry
                registry().unregister_source(name, expected=_snapshot)
                return {"error": "engine collected"}
            return m.snapshot()

        self._snapshot_fn = _snapshot
        profiler.register_metrics_source(name, _snapshot)

    # ------------------------------------------------- mesh groundwork
    def _init_mesh(self, mesh):
        """Resolve EngineConfig.mesh into (mesh, shardings).

        tp groundwork (ROADMAP item 3): the paged KV pools shard along
        the HEAD axis (pool axis 1) and every other operand is either
        mesh-replicated or weight-sharded by :meth:`_param_sharding`;
        all programs then lower as SPMD computations over the mesh.
        A ``{"tp": n}`` dict builds a mesh over the first n devices
        (virtual CPU devices in tests, real chips on TPU).
        """
        if mesh is None:
            self._mesh = None
            self._repl_sharding = None
            self._pool_sharding = None
            return
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        if isinstance(mesh, dict):
            axes = tuple(mesh.keys())
            shape = tuple(int(s) for s in mesh.values())
            n = 1
            for s in shape:
                n *= s
            devices = jax.devices()
            if n > len(devices):
                raise ValueError(
                    f"mesh {dict(mesh)} needs {n} devices but only "
                    f"{len(devices)} are visible")
            mesh = Mesh(np.asarray(devices[:n]).reshape(shape), axes)
        tp = int(mesh.shape.get("tp", 1))
        if tp > 1 and self._num_heads % tp:
            raise ValueError(
                f"num_heads {self._num_heads} must divide by the tp "
                f"extent {tp} to shard KV pools along the head axis")
        self._mesh = mesh
        self._repl_sharding = NamedSharding(mesh, PartitionSpec())
        # pool layout [num_pages, heads, page_size, head_dim]: axis 1
        # IS the head axis
        self._pool_sharding = NamedSharding(
            mesh, PartitionSpec(None, "tp"))

    def _param_sharding(self, arr):
        """Head-axis weight sharding heuristic: shard the LAST axis
        whose extent is a multiple of hidden (= heads * head_dim) over
        tp — column-parallel projections and embeddings — and replicate
        everything else (LN scales, biases, scalar state).  A
        best-effort groundwork rule: any consistent choice is
        numerically a relayout, and GSPMD inserts the collectives."""
        from jax.sharding import NamedSharding, PartitionSpec
        tp = int(self._mesh.shape.get("tp", 1))
        hidden = self._num_heads * self._head_dim
        if tp > 1 and getattr(arr, "ndim", 0) >= 2:
            for ax in range(arr.ndim - 1, -1, -1):
                d = int(arr.shape[ax])
                if d and d % hidden == 0 and (d // tp) % (
                        self._head_dim) == 0:
                    spec = [None] * arr.ndim
                    spec[ax] = "tp"
                    return NamedSharding(self._mesh,
                                         PartitionSpec(*spec))
        return self._repl_sharding

    def _place(self, value, sharding=None):
        """Device placement for program operands: plain ``asarray``
        off-mesh; an explicit mesh placement (replicated by default) on
        the mesh, so every input of an SPMD program lives on the same
        device set."""
        if self._mesh is None:
            return jnp.asarray(value)
        return jax.device_put(np.asarray(value) if not isinstance(
            value, jax.Array) else value,
            sharding if sharding is not None else self._repl_sharding)

    @property
    def program_fingerprint(self):
        """The AOT-cache fingerprint (None when no cache is attached):
        model config + param tree + engine geometry + mesh + jax/backend
        versions — docs/serving.md 'AOT program cache' has the schema."""
        return self._program_fp

    # ------------------------------------------------------------ API
    def _resolve_params(self, sampling_params):
        """Fill in the engine-level eos default."""
        sp = sampling_params or SamplingParams(
            eos_token_id=self.config.eos_token_id)
        if sp.eos_token_id is None and self.config.eos_token_id is not None:
            sp = SamplingParams(
                max_new_tokens=sp.max_new_tokens,
                temperature=sp.temperature, top_k=sp.top_k,
                top_p=sp.top_p, seed=sp.seed,
                eos_token_id=self.config.eos_token_id)
        return sp

    def _validate_request(self, prompt, sp):
        """Raise ValueError unless (prompt, sp) is servable end to end —
        called BEFORE anything is enqueued, so a bad request can never
        strand earlier ones in the queue."""
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        total_max = len(prompt) + sp.max_new_tokens
        if total_max > self.config.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({sp.max_new_tokens}) = {total_max} exceeds "
                f"max_model_len {self.config.max_model_len}")
        # the WORST-CASE replay length must be bucketable, not just the
        # bare prompt: an eviction after g generated tokens (g can reach
        # max_new_tokens - 1) replays prompt + g through prefill
        self.scheduler.bucket_for_len(len(prompt) + sp.max_new_tokens - 1)
        # the request must be SERVABLE alone on an empty pool: its final
        # length's pages, and — the admission gate's view — its worst
        # replay length plus the scheduler's growth reserve (otherwise
        # add_request accepts work that deadlocks the queue forever)
        need_total = max(
            self._alloc.pages_needed(total_max, self.config.page_size),
            self.scheduler.pages_for_prompt(total_max - 1))
        if need_total > self.config.num_pages - 1:
            raise ValueError(
                f"request needs up to {need_total} pages (incl. the "
                f"admission growth reserve) but the pool only has "
                f"{self.config.num_pages - 1}")

    def add_request(self, prompt_token_ids, sampling_params=None,
                    stream=None):
        """Queue one request; returns its request id.  Admission happens
        at the next :meth:`step` boundary.  Raises
        :class:`AdmissionRejected` under backpressure (waiting queue at
        `max_queue_depth`, or health DRAINING)."""
        sp = self._resolve_params(sampling_params)
        prompt = [int(t) for t in prompt_token_ids]
        self._validate_request(prompt, sp)
        if not self.health.admitting:
            self.metrics.requests_rejected += 1
            raise AdmissionRejected(
                "draining",
                f"engine {self._metrics_name} page-pool pressure "
                f"{self.health.last_pressure:.2f}")
        rid = f"req-{self._next_id}"
        req = Request(rid, prompt, sp, arrival_index=self._next_id,
                      stream=stream)
        # distributed-trace identity: the router installs the request's
        # TraceContext ambiently (use_context) around this call — local
        # single-engine use leaves it None and nothing changes
        req.trace = current_context()
        req.arrive_t = self.metrics.clock()
        if sp.deadline_s is not None:
            req.deadline_t = req.arrive_t + sp.deadline_s
        try:
            self.scheduler.enqueue(req)
        except AdmissionRejected:
            self.metrics.requests_rejected += 1
            raise
        self._next_id += 1
        self._requests[rid] = req
        self.metrics.requests_received += 1
        return rid

    def adopt_request(self, prompt_token_ids, sampling_params=None,
                      generated_token_ids=(), stream=None, streamed=None,
                      arrive_t=None, arrival_index=None):
        """Router failover hook: enqueue a request that already
        generated tokens on ANOTHER replica.  The adopted request
        enters at the queue FRONT in the evicted-replay posture —
        ``generated_token_ids`` ride along in ``replay_token_ids``, the
        replay prefill reconstructs the KV cache, and the (seed,
        absolute-position) sampler regenerates the continuation
        token-identically — so a replica crash or drain migrates work
        with zero data loss and zero token divergence.

        `streamed` marks how many tokens the ORIGIN already delivered
        to the stream callback (default: all of `generated_token_ids`),
        so the new replica never re-streams them.  `arrive_t` carries
        the ORIGINAL arrival time (same `metrics.clock` timebase) so a
        `deadline_s` TTL keeps counting from first arrival instead of
        restarting on every migration, and `arrival_index` carries the
        caller's global age ordering so the fleet-oldest request does
        not become this engine's freshest — and therefore preferred —
        LIFO preemption victim.  Raises
        :class:`AdmissionRejected` while this engine is DRAINING, and
        ``ValueError`` when the replayed request could never be served
        here — both leave the request with the caller."""
        sp = self._resolve_params(sampling_params)
        prompt = [int(t) for t in prompt_token_ids]
        generated = [int(t) for t in generated_token_ids]
        self._validate_request(prompt, sp)
        if len(generated) >= sp.max_new_tokens:
            raise ValueError(
                f"request already finished ({len(generated)} of "
                f"{sp.max_new_tokens} tokens) — nothing to adopt")
        if not self.health.admitting:
            self.metrics.requests_rejected += 1
            raise AdmissionRejected(
                "draining",
                f"engine {self._metrics_name} page-pool pressure "
                f"{self.health.last_pressure:.2f}")
        rid = f"req-{self._next_id}"
        req = Request(rid, prompt, sp,
                      arrival_index=(self._next_id if arrival_index
                                     is None else int(arrival_index)),
                      stream=stream)
        req.output_token_ids = generated
        req._streamed = len(generated) if streamed is None \
            else min(int(streamed), len(generated))
        # adopted == evicted-elsewhere: requests_admitted/ttft are the
        # ORIGIN replica's events, not this one's
        req.num_evictions = 1
        req.trace = current_context()
        req.arrive_t = (self.metrics.clock() if arrive_t is None
                        else float(arrive_t))
        # resume latency: adoption on THIS engine to its first token
        # (the ttft_decode stage; absent for never-migrated requests)
        req._resume_t = self.metrics.clock()
        if sp.deadline_s is not None:
            req.deadline_t = req.arrive_t + sp.deadline_s
        self.scheduler.requeue_front(req)
        self._next_id += 1
        self._requests[rid] = req
        self.metrics.requests_adopted += 1
        with span("serving.adopt", ctx=req.trace, request=rid,
                  generated=len(generated)):
            pass
        return rid

    def release_waiting(self):
        """Router drain hook: withdraw every still-QUEUED request
        (freshly waiting or evicted-and-requeued — none own slots or
        pages) and hand the Request objects to the caller, which now
        owns their fate (typically ``adopt_request`` on another
        replica).  Running requests are untouched: their pages are
        local, so they finish here."""
        reqs = self.scheduler.drain_waiting()
        for r in reqs:
            self._requests.pop(r.request_id, None)
        if reqs:
            with span("serving.release_waiting", count=len(reqs)):
                pass
        return reqs

    # ------------------------------------- disaggregated page handoff
    def export_page_state(self, request_id, release=True):
        """Disaggregated prefill→decode hook: snapshot one RUNNING
        request's KV pages + scheduler state into a host dict a DECODE
        engine can :meth:`import_page_state` — the page-moving
        counterpart of token-only adoption, for when re-running prefill
        on the target is the cost being disaggregated away.

        The payload carries, per layer, the request's owned pages
        gathered from the (possibly quantized ``(codes, scales)``)
        pools, plus prompt/generated tokens, sampling params, stream
        watermark, deadline AGE (``metrics.clock`` is per-process — the
        absolute ``arrive_t`` never crosses a process boundary), and
        the pool geometry the importer validates against.  With
        `release` (default) the request leaves this engine entirely —
        slot, pages and live-table entry — so prefill workers stay
        empty-handed between handoffs."""
        req = self._requests.get(request_id)
        if req is None or req.slot is None:
            raise ValueError(
                f"request {request_id!r} is not running here — only a "
                f"RUNNING (slot-owning) request has pages to export")
        slot = req.slot
        cfg = self.config
        L = int(self._lens[slot])
        pages = list(self._alloc.owned_pages(slot))
        layers = []
        for k_pool, v_pool in zip(self._k_pools, self._v_pools):
            if self._kv_quant is None:
                layers.append({
                    "k": np.asarray(k_pool)[pages],
                    "v": np.asarray(v_pool)[pages]})
            else:
                layers.append({
                    "k_codes": np.asarray(k_pool[0])[pages],
                    "k_scales": np.asarray(k_pool[1])[pages],
                    "v_codes": np.asarray(v_pool[0])[pages],
                    "v_scales": np.asarray(v_pool[1])[pages]})
        sp = req.sampling_params
        state = {
            "prompt_token_ids": list(req.prompt_token_ids),
            "output_token_ids": list(req.output_token_ids),
            "streamed": int(req._streamed),
            "age_s": max(0.0, self.metrics.clock() - req.arrive_t),
            "arrival_index": int(req.arrival_index),
            "len": L,
            "sampling_params": {
                "max_new_tokens": sp.max_new_tokens,
                "temperature": sp.temperature,
                "top_k": sp.top_k, "top_p": sp.top_p, "seed": sp.seed,
                "eos_token_id": sp.eos_token_id,
                "deadline_s": sp.deadline_s,
            },
            "geometry": {
                "page_size": cfg.page_size,
                "num_layers": self._num_layers,
                "num_heads": self._num_heads,
                "head_dim": self._head_dim,
                "kv_cache_dtype": cfg.kv_cache_dtype,
                "dtype": str(np.dtype(cfg.dtype)),
            },
            "layers": layers,
        }
        if req.trace is not None:
            # trace identity rides the handoff blob so the decode
            # engine's spans join the originating request's trace
            state["trace"] = req.trace.to_dict()
        with span("serving.page_export", ctx=req.trace,
                  request=request_id, pages=len(pages), tokens=L,
                  release=bool(release)):
            if release:
                req.transition(RequestState.EVICTED)
                self._release_slot(req)
                self._requests.pop(request_id, None)
        return state

    def import_page_state(self, state, stream=None):
        """Decode-side half of the disaggregated handoff: rebuild the
        exported request in THIS engine — allocate fresh pages, write
        the shipped KV blocks into the local pools (eager ``.at[]``
        scatter: no new compiled program, the bounded-compile contract
        is untouched), and enter the request directly at DECODE.  Token
        identity is inherited from the deterministic ``(seed, absolute
        position)`` sampler: the next sampled position is exactly where
        the prefill engine left off.  Returns the new request id.

        Raises ``ValueError`` on a geometry mismatch and
        :class:`AdmissionRejected` when no slot/pages are free or this
        engine is DRAINING (the exporter still holds the state dict and
        can retry elsewhere)."""
        cfg = self.config
        geo = state["geometry"]
        mine = {"page_size": cfg.page_size,
                "num_layers": self._num_layers,
                "num_heads": self._num_heads,
                "head_dim": self._head_dim,
                "kv_cache_dtype": cfg.kv_cache_dtype,
                "dtype": str(np.dtype(cfg.dtype))}
        for k, want in mine.items():
            if geo.get(k) != want:
                raise ValueError(
                    f"page-state geometry mismatch on {k!r}: exporter "
                    f"{geo.get(k)!r} vs importer {want!r}")
        sp = SamplingParams(**state["sampling_params"])
        prompt = [int(t) for t in state["prompt_token_ids"]]
        generated = [int(t) for t in state["output_token_ids"]]
        self._validate_request(prompt, sp)
        L = int(state["len"])
        if L != len(prompt) + len(generated) - 1:
            raise ValueError(
                f"page-state cache length {L} does not match "
                f"prompt+generated-1 = "
                f"{len(prompt) + len(generated) - 1} (the newest "
                f"token's KV is written by the NEXT decode step)")
        if not self.health.admitting:
            self.metrics.requests_rejected += 1
            raise AdmissionRejected(
                "draining",
                f"engine {self._metrics_name} page-pool pressure "
                f"{self.health.last_pressure:.2f}")
        n_pages = len(state["layers"][0][
            "k" if self._kv_quant is None else "k_codes"])
        try:
            slot = self._slots.index(None)
        except ValueError:
            self.metrics.requests_rejected += 1
            raise AdmissionRejected(
                "no_slot", f"engine {self._metrics_name} has no free "
                f"decode slot for an imported request")
        if not self._alloc.can_allocate(slot, n_pages):
            self.metrics.requests_rejected += 1
            raise AdmissionRejected(
                "no_pages",
                f"engine {self._metrics_name} cannot allocate "
                f"{n_pages} pages for an imported request")
        rid = f"req-{self._next_id}"
        req = Request(rid, prompt, sp,
                      arrival_index=int(state.get(
                          "arrival_index", self._next_id)),
                      stream=stream)
        req.output_token_ids = generated
        req._streamed = min(int(state.get("streamed", len(generated))),
                            len(generated))
        req.num_evictions = 1     # admitted/ttft were the exporter's
        req.trace = (TraceContext.from_dict(state.get("trace"))
                     or current_context())
        req.arrive_t = self.metrics.clock() - float(
            state.get("age_s", 0.0))
        req._resume_t = self.metrics.clock()
        if sp.deadline_s is not None:
            req.deadline_t = req.arrive_t + sp.deadline_s
        self._next_id += 1
        self._slots[slot] = req
        req.slot = slot
        pages = [page for _pos, page in self._alloc.allocate(slot,
                                                             n_pages)]
        for pos, page in enumerate(pages):
            self._tables[slot, pos] = page
        idx = np.asarray(pages)
        for li in range(self._num_layers):
            blk = state["layers"][li]
            if self._kv_quant is None:
                self._k_pools[li] = self._k_pools[li].at[idx].set(
                    jnp.asarray(blk["k"]))
                self._v_pools[li] = self._v_pools[li].at[idx].set(
                    jnp.asarray(blk["v"]))
            else:
                kc, ks = self._k_pools[li]
                vc, vs = self._v_pools[li]
                self._k_pools[li] = (
                    kc.at[idx].set(jnp.asarray(blk["k_codes"])),
                    ks.at[idx].set(jnp.asarray(blk["k_scales"])))
                self._v_pools[li] = (
                    vc.at[idx].set(jnp.asarray(blk["v_codes"])),
                    vs.at[idx].set(jnp.asarray(blk["v_scales"])))
        self._lens[slot] = L
        req.transition(RequestState.PREFILL)
        req.transition(RequestState.DECODE)
        self._requests[rid] = req
        self.metrics.requests_adopted += 1
        with span("serving.page_import", ctx=req.trace, request=rid,
                  pages=n_pages, tokens=L):
            pass
        return rid

    def has_unfinished(self):
        return (self.scheduler.has_waiting()
                or any(r is not None for r in self._slots))

    # live admission telemetry — the same signals the step-boundary
    # scrape gauges export, read at the source so an in-process router
    # balancing a BURST of admissions between steps sees each one land
    @property
    def queue_depth(self):
        return self.scheduler.queue_depth

    @property
    def num_running(self):
        return sum(1 for r in self._slots if r is not None)

    @property
    def page_occupancy(self):
        total = self.config.num_pages - 1      # page 0 reserved
        if not total:
            return 0.0
        return (total - self._alloc.num_free_pages) / total

    def step(self):
        """One engine iteration: admit + prefill new requests at the
        step boundary, then one continuous-batched decode step.  Returns
        ``[(request_id, token_id, finished), ...]`` for tokens produced
        this step; a preemption surfaces as ``(request_id, None, False)``
        (the request re-enters the queue and will be replayed)."""
        events = []
        self._expire_deadlines(events)
        with span("serving.admit"):
            admitted = self._admit(events)
        running = [r for r in self._slots if r is not None]
        if running:
            self._decode_step(events)
        elif not admitted and self.scheduler.has_waiting() \
                and self.health.admitting:
            # (DRAINING holds the queue on purpose — not a deadlock)
            head = self.scheduler.peek()
            raise RuntimeError(
                f"scheduler deadlock: nothing running and request "
                f"{head.request_id} (prompt {len(head.replay_token_ids)} "
                f"tokens) cannot be admitted — the page pool "
                f"({self._alloc.num_free_pages} free) is too small")
        self._refresh_gauges()
        return events

    def generate(self, prompts, sampling_params=None):
        """Sync facade: serve `prompts` (list of token-id lists) to
        completion; returns :class:`GenerationResult` per prompt in
        input order."""
        if prompts and isinstance(prompts[0], int):
            raise TypeError("generate expects a LIST of prompts "
                            "(each a list of token ids)")
        if isinstance(sampling_params, (list, tuple)):
            if len(sampling_params) != len(prompts):
                raise ValueError("one SamplingParams per prompt required")
            sps = list(sampling_params)
        else:
            sps = [sampling_params] * len(prompts)
        # all-or-nothing: validate the whole batch BEFORE enqueueing so
        # a bad prompt can't strand its predecessors in the queue
        pairs = [([int(t) for t in p], self._resolve_params(sp))
                 for p, sp in zip(prompts, sps)]
        for prompt, sp in pairs:
            self._validate_request(prompt, sp)
        rids = []
        try:
            for p, sp in pairs:
                rids.append(self.add_request(p, sp))
        except AdmissionRejected:
            # all-or-nothing under backpressure too: withdraw the
            # partial batch (no step() has run, so the withdrawn
            # requests own no slots or pages) instead of stranding it
            # in the bounded queue with no rids returned
            for r in rids:
                self.scheduler.withdraw(self._requests.pop(r))
            raise
        reqs = [self._requests[r] for r in rids]   # hold refs: _finish
        while self.has_unfinished():               # moves them out of
            self.step()                            # the live table
        for r in rids:
            self.finished_requests.pop(r, None)
        return [GenerationResult(req) for req in reqs]

    def shutdown(self):
        """Unregister from the profiler metrics registry and release
        this engine's claim on its registry-owned instruments (shared
        instruments survive until the last same-named engine goes)."""
        from paddle_tpu.observability.metrics import registry
        registry().unregister_source(self._metrics_name,
                                     expected=self._snapshot_fn)
        self.metrics.release()

    # ----------------------------------------------------- deadlines
    def _expire_deadlines(self, events):
        """Step-boundary deadline sweep: queued requests past their TTL
        finish with reason "deadline"; running ones release their slot
        and pages first.  Deterministic — driven by `metrics.clock`
        and queue/slot order only."""
        now = self.metrics.clock()
        expired = self.scheduler.pop_expired(now)
        for slot in range(self.config.max_num_seqs):
            r = self._slots[slot]
            if r is not None and r.past_deadline(now):
                expired.append(r)
        for req in expired:
            with span("serving.deadline", request=req.request_id,
                      state=req.state.value,
                      overrun_s=round(now - req.deadline_t, 4)):
                self.metrics.requests_expired += 1
                self._finish(req, "deadline", now)
                req.deliver(finished=True)
                events.append((req.request_id, None, True))

    # ----------------------------------------------------- admission
    def _free_slot_count(self):
        return sum(1 for r in self._slots if r is None)

    def _admit(self, events):
        admitted = 0
        while True:
            req = self.scheduler.pop_admissible(
                self._free_slot_count(), self._alloc.num_free_pages)
            if req is None:
                break
            self._prefill(req, events)
            admitted += 1
        return admitted

    def _prefill(self, req, events):
        cfg = self.config
        t0 = self.metrics.clock()
        req.transition(RequestState.PREFILL)
        tokens = req.replay_token_ids
        L = len(tokens)
        bucket = self.scheduler.bucket_for_len(L)
        with span("serving.prefill", ctx=req.trace,
                  request=req.request_id, bucket=bucket, tokens=L):
            self._prefill_inner(req, events, cfg, t0, tokens, L, bucket)

    def _prefill_inner(self, req, events, cfg, t0, tokens, L, bucket):
        slot = self._slots.index(None)
        self._slots[slot] = req
        req.slot = slot

        need = self._alloc.pages_needed(L, cfg.page_size)
        for pos, page in self._alloc.allocate(slot, need):
            self._tables[slot, pos] = page

        ids = np.zeros((1, bucket), np.int32)
        ids[0, :L] = tokens
        pos_ids = np.arange(bucket, dtype=np.int32)[None, :]
        length = np.array([L], np.int32)

        fn = self._get_prefill(bucket)
        last_logits, self._k_pools, self._v_pools = fn(
            self._params, self._k_pools, self._v_pools,
            self._place(self._tables[slot:slot + 1]), self._place(ids),
            self._place(pos_ids), self._place(length))
        self._lens[slot] = L

        tok = self._sample(last_logits, [req], width=1)[0]
        now = self.metrics.clock()
        self.metrics.prefill_steps += 1
        self.metrics.prefill_step_s.observe(now - t0)
        self.metrics.prompt_tokens += L
        if req.num_evictions == 0:
            self.metrics.requests_admitted += 1
            self.metrics.ttft.observe(now - req.arrive_t)
            # stage decomposition: for a fresh request TTFT is exactly
            # queue-wait (arrival -> prefill start) + prefill
            self.metrics.ttft_queue.observe(max(0.0, t0 - req.arrive_t))
            self.metrics.ttft_prefill.observe(max(0.0, now - t0))
        req.append_token(tok, now=now)
        self._observe_resume(req, now)
        self.metrics.generated_tokens += 1
        self._post_token(req, events, now)
        if not req.is_finished:
            req.transition(RequestState.DECODE)

    # -------------------------------------------------------- decode
    def _decode_step(self, events):
        with span("serving.decode"):
            self._decode_step_inner(events)

    def _decode_step_inner(self, events):
        cfg = self.config
        t0 = self.metrics.clock()
        # chaos hook: injected pool exhaustion drives ONE deterministic
        # preemption round through the REAL victim-selection path (the
        # same code a genuinely dry pool exercises below)
        spec = _fire("serving.pool", step=self.metrics.decode_steps)
        if spec is not None and spec.kind == "pool_exhaust":
            for _ in range(int(spec.payload.get("victims", 1))):
                victim = self.scheduler.select_victim(
                    [r for r in self._slots if r is not None])
                if victim is None:
                    break
                self._evict(victim, events)
                note_recovery("serving.pool", "pool_exhaust",
                              victim=victim.request_id)
        # capacity pass: every live row must fit one more token; the
        # pool running dry preempts the latest-arrived running request
        for slot in range(cfg.max_num_seqs):
            req = self._slots[slot]
            if req is None:
                continue
            need = self._alloc.pages_needed(
                int(self._lens[slot]) + 1, cfg.page_size)
            while not self._alloc.can_allocate(slot, need):
                victim = self.scheduler.select_victim(
                    [r for r in self._slots if r is not None])
                if victim is None:
                    raise RuntimeError(
                        "paged pool exhausted with nothing left to "
                        "preempt")
                self._evict(victim, events)
                if victim is req:
                    break
            if self._slots[slot] is None:
                continue                       # row preempted itself
            for pos, page in self._alloc.allocate(slot, need):
                self._tables[slot, pos] = page

        live = [(s, r) for s, r in enumerate(self._slots) if r is not None]
        if not live:
            return
        tokens = np.zeros((cfg.max_num_seqs, 1), np.int32)
        for s, r in live:
            tokens[s, 0] = r.output_token_ids[-1]

        fn = self._get_decode()
        guard_args = ()
        if cfg.guard:
            guard_args = (self._place(self._poison_vector(live)),)
        try:
            # chaos hook: `exception` faults here simulate a crashed
            # decode (payload `request_id` names the offender)
            _fire("serving.decode", step=self.metrics.decode_steps)
            out = fn(
                self._params, self._k_pools, self._v_pools,
                self._place(self._tables), self._place(self._lens),
                self._place(tokens), *guard_args)
        except Exception as e:
            if not cfg.crash_safe_decode:
                raise
            self._recover_decode_fault(e, events)
            return
        if cfg.guard:
            logits, self._k_pools, self._v_pools, flags = out
            live = self._quarantine_flagged(live, flags, events)
        else:
            logits, self._k_pools, self._v_pools = out
        self._decode_fault_streak = 0

        reqs = [self._slots[s] for s in range(cfg.max_num_seqs)]
        toks = self._sample(logits, reqs, width=cfg.max_num_seqs)
        for s, r in live:
            self._lens[s] += 1
        now = self.metrics.clock()
        self.metrics.decode_steps += 1
        self.metrics.decode_step_s.observe(now - t0)
        for s, r in live:
            if r.last_token_t is not None:
                self.metrics.inter_token.observe(now - r.last_token_t)
            r.append_token(toks[s], now=now)
            self._observe_resume(r, now)
            self.metrics.generated_tokens += 1
            self._post_token(r, events, now)

    def _poison_vector(self, live):
        """The guarded decode's injection operand: zeros in production;
        a ``serving.logits`` fault poisons the victim's row (nan_grad →
        NaN, bitflip → +inf) so detection is exercised through the REAL
        compiled program — deterministic, and the program never
        changes."""
        cfg = self.config
        poison = np.zeros((cfg.max_num_seqs, 1), np.float32)
        spec = _fire("serving.logits", step=self.metrics.decode_steps)
        if spec is not None and spec.kind in ("bitflip", "nan_grad") \
                and live:
            rid = spec.payload.get("request_id")
            if rid is not None:
                # request-targeted fault: if the target is no longer
                # live (finished/quarantined), the fault is spent —
                # never redirect the poison onto an innocent request
                victim = next((r for _s, r in live
                               if r.request_id == rid), None)
            else:
                victim = max((r for _s, r in live),
                             key=lambda r: r.arrival_index)
            if victim is not None:
                poison[victim.slot, 0] = (np.nan
                                          if spec.kind == "nan_grad"
                                          else np.inf)
        return poison

    def _quarantine_flagged(self, live, flags, events):
        """Guard verdicts -> evictions: every flagged live request is
        evicted-and-requeued (the crash-safe path — its replay prefill
        rebuilds clean pools from prompt + generated tokens, and its
        freed pages are rewritten before any read), EXCEPT a request
        already guard-evicted ``guard_requeue_limit`` times, which
        finishes with ``finish_reason="anomaly"`` (a deterministic
        poison must not replay forever).  Returns the surviving live
        list."""
        fl = np.asarray(flags)
        flagged = [(s, r) for s, r in live if fl[s].any()]
        if not flagged:
            return live
        from paddle_tpu.resilience.sentinel import note_anomaly
        now = self.metrics.clock()
        for s, r in flagged:
            kind = ("nan_logits" if fl[s, 0]
                    else "scale_overflow")
            note_anomaly(kind, "serving.decode",
                         step=self.metrics.decode_steps,
                         request=r.request_id)
            r.num_guard_evictions = getattr(
                r, "num_guard_evictions", 0) + 1
            self.metrics.guard_anomalies += 1
            with span("serving.guard", request=r.request_id, kind=kind,
                      evictions=r.num_guard_evictions):
                if r.num_guard_evictions > \
                        self.config.guard_requeue_limit:
                    self._finish(r, "anomaly", now)
                    r.deliver(finished=True)
                    events.append((r.request_id, None, True))
                else:
                    self._evict(r, events)
            note_recovery("serving.decode", kind,
                          request=r.request_id)
        return [(s, r) for s, r in live if self._slots[s] is r]

    def _recover_decode_fault(self, exc, events):
        """Crash-safe decode: a failed decode program left no state
        behind (pools/lens update only on success, page grows are
        idempotent), so the engine evicts-and-requeues the OFFENDING
        request and keeps serving.  The offender is the exception's
        `request_id` when it names one (injected faults, request-
        poisoned inputs), else the latest-arrived live request — the
        same deterministic victim order preemption uses.  Requeued, not
        killed: the replay prefill regenerates its tokens exactly, so
        recovery is token-identical for every surviving request.

        A full batch of consecutive faults (streak > max_num_seqs)
        means the fault is NOT request-local (wedged device, poisoned
        weights) — rethrow rather than spin forever."""
        live = [r for r in self._slots if r is not None]
        self._decode_fault_streak += 1
        if not live or self._decode_fault_streak > self.config.max_num_seqs:
            raise exc
        rid = getattr(exc, "request_id", None)
        offender = next((r for r in live if r.request_id == rid), None)
        if offender is None:
            offender = max(live, key=lambda r: r.arrival_index)
        with span("serving.decode_fault", request=offender.request_id,
                  exc=type(exc).__name__, streak=self._decode_fault_streak):
            self._evict(offender, events)
        self.metrics.decode_fault_recoveries += 1
        note_recovery("serving.decode", "exception",
                      request=offender.request_id,
                      exc=type(exc).__name__)

    # ------------------------------------------------------ sampling
    def _sample(self, logits, reqs, width):
        """reqs: per-row Request or None (padding rows).  Position is
        the ABSOLUTE index of the token being sampled = the row's cache
        length AFTER its input token was appended — which is exactly
        `total_len` host-side."""
        seeds = np.zeros((width,), np.int32)
        pos = np.zeros((width,), np.int32)
        temps = np.zeros((width,), np.float32)
        top_ks = np.zeros((width,), np.int32)
        top_ps = np.ones((width,), np.float32)
        for i, r in enumerate(reqs):
            if r is None:
                continue
            sp = r.sampling_params
            seeds[i] = sp.seed
            pos[i] = r.total_len
            temps[i] = sp.temperature
            top_ks[i] = sp.top_k
            top_ps[i] = sp.top_p
        fn = self._get_sampler(width)
        out = fn(self._place(logits), self._place(seeds),
                 self._place(pos), self._place(temps),
                 self._place(top_ks), self._place(top_ps))
        return [int(t) for t in np.asarray(out)]

    # ------------------------------------------------- finish / evict
    def _observe_resume(self, req, now):
        """First token after an adoption/import on THIS engine closes
        the ttft_decode stage (resume latency of migrated work)."""
        if req._resume_t is not None:
            self.metrics.ttft_decode.observe(max(0.0,
                                                 now - req._resume_t))
            req._resume_t = None

    def _post_token(self, req, events, now):
        reason = req.should_stop()
        if reason is not None:
            self._finish(req, reason, now)
        req.deliver(finished=req.is_finished)
        events.append((req.request_id, req.output_token_ids[-1],
                       req.is_finished))

    def _finish(self, req, reason, now):
        req.finish_reason = reason
        req.transition(RequestState.FINISHED)
        if req.slot is not None:     # queued deadline expiry has none
            self._release_slot(req)
        req.finish_t = now
        self.metrics.requests_finished += 1
        self.metrics.e2e_latency.observe(now - req.arrive_t)
        if req.trace is not None:
            # the trace's terminal marker (fleettrace timelines key on
            # it) — recorded ONLY for traced requests, so untraced
            # engines see zero new spans
            with span("serving.finish", ctx=req.trace,
                      request=req.request_id, reason=reason,
                      tokens=len(req.output_token_ids)):
                pass
        # move out of the live table so a perpetual serving loop cannot
        # accumulate one Request (+ stream closure) per request served
        self._requests.pop(req.request_id, None)
        self.finished_requests[req.request_id] = req
        while len(self.finished_requests) > self.config.finished_retention:
            self.finished_requests.popitem(last=False)

    def _evict(self, req, events):
        """Deterministic preemption: free everything, requeue at the
        queue front; the replay prefill later reconstructs the cache
        from prompt + generated tokens (token-identical, see sampler)."""
        with span("serving.preempt", request=req.request_id,
                  generated=len(req.output_token_ids)):
            req.transition(RequestState.EVICTED)
            self._release_slot(req)
            req.num_evictions += 1
            self.metrics.requests_evicted += 1
            self.scheduler.requeue_front(req)
            events.append((req.request_id, None, False))

    def _release_slot(self, req):
        slot = req.slot
        self._alloc.release(slot)
        self._tables[slot, :] = 0
        self._lens[slot] = 0
        self._slots[slot] = None
        req.slot = None

    def _refresh_gauges(self):
        m = self.metrics
        m.queue_depth = self.scheduler.queue_depth
        m.running = sum(1 for r in self._slots if r is not None)
        m.pages_in_use = (self.config.num_pages - 1
                          - self._alloc.num_free_pages)
        state = self.health.update(
            m.pages_in_use / m.pages_total if m.pages_total else 0.0)
        m.health = state.name.lower()
        m.sync_gauges()    # queue-depth / page-occupancy scrape gauges

    # ------------------------------------------------- compiled steps
    def _run_model(self, params, ids, pos_ids, ctx):
        """Traced: rebind params, run the cache-aware forward."""
        sd = self._model.state_dict()
        saved = [(t, t._value) for t in sd.values()]
        try:
            for k, t in sd.items():
                t._value = params[k]
            with no_grad():
                out = self._model(Tensor(ids), position_ids=Tensor(pos_ids),
                                  kv_ctx=ctx)
            return out._value
        finally:
            for t, v in saved:
                t._value = v

    def _step_out_shardings(self):
        """out_shardings for the prefill/decode step programs in mesh
        mode (None otherwise): logits replicated, pools keeping their
        head-axis sharding — pinning the output layout to the input
        layout is what keeps the pool arrays reusable call-over-call
        without a resharding copy (or a surprise cache miss)."""
        if self._mesh is None:
            return None
        # quantized pool entries are (codes, scales) pairs; the same
        # P(None, "tp") spec shards codes on the head axis (axis 1 of
        # [pages, heads, page, dim]) and scales on theirs (axis 1 of
        # [pages, heads])
        pool_sh = (self._pool_sharding if self._kv_quant is None
                   else (self._pool_sharding, self._pool_sharding))
        return (self._repl_sharding,
                [pool_sh] * self._num_layers,
                [pool_sh] * self._num_layers)

    def _prefill_program(self, bucket):
        """(fn, example_args, donate, out_shardings) for one prefill
        bucket — shared by the compile path and the shardlint self-audit
        (which traces the SAME program, never a lookalike)."""
        cfg = self.config

        def prefill(params, k_pools, v_pools, row_table, ids, pos_ids,
                    length):
            ctx = PagedKVContext(k_pools, v_pools, row_table, length,
                                 cfg.page_size, "prefill",
                                 quant=self._kv_quant)
            logits = self._run_model(params, ids, pos_ids, ctx)
            # logits [1, bucket, V] -> the last REAL token's row
            last = jnp.take_along_axis(
                logits, (length - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            return (last.astype(jnp.float32), ctx.k_pools, ctx.v_pools)

        return prefill, (
            self._params, self._k_pools, self._v_pools,
            jnp.zeros((1, cfg.max_pages_per_seq), jnp.int32),
            jnp.zeros((1, bucket), jnp.int32),
            jnp.zeros((1, bucket), jnp.int32),
            jnp.zeros((1,), jnp.int32)), (1, 2), \
            self._step_out_shardings()

    def _guard_flags(self, logits, k_pools, v_pools, tables, lens):
        """Traced per-slot anomaly flags ``[B, 2]`` f32: column 0 is
        the logit finite-check (any non-finite value in the row's
        logits), column 1 the quantized-KV scale-overflow check (a
        non-finite — or above ``guard_scale_limit`` — page scale on
        any page the row actually uses, any layer).  Gathers touch
        only the tiny ``[N, h]`` scale planes, so the guard's decode
        bytes are noise next to the attention reads."""
        cfg = self.config
        bad_logits = jnp.any(~jnp.isfinite(logits), axis=-1)     # [B]
        if self._kv_quant is None:
            bad_scale = jnp.zeros_like(bad_logits)
        else:
            P_ = tables.shape[1]
            used = ((jnp.arange(P_, dtype=jnp.int32) * cfg.page_size)
                    [None, :] < (lens + 1)[:, None])             # [B, P]
            limit = cfg.guard_scale_limit
            bad_scale = jnp.zeros(logits.shape[0], jnp.bool_)
            for kq, vq in zip(k_pools, v_pools):
                for _codes, scales in (kq, vq):
                    s = scales[tables]                           # [B,P,h]
                    bad = ~jnp.isfinite(s)
                    if limit is not None:
                        bad = bad | (s > limit)
                    bad_scale = bad_scale | jnp.any(
                        bad & used[:, :, None], axis=(1, 2))
        return jnp.stack([bad_logits, bad_scale],
                         axis=-1).astype(jnp.float32)

    def _decode_program(self):
        cfg = self.config

        if cfg.guard:
            # sentinel-guarded decode: one extra [B, 1] poison operand
            # (all zeros in production — the fault-injection hook adds
            # NaN/inf to a victim row, so injection never changes the
            # compiled program) and one extra [B, 2] anomaly-flag
            # output.  Still ONE decode program for the engine's life.
            def decode(params, k_pools, v_pools, tables, lens, tokens,
                       poison):
                ctx = PagedKVContext(k_pools, v_pools, tables, lens,
                                     cfg.page_size, "decode",
                                     quant=self._kv_quant)
                logits = self._run_model(params, tokens, lens[:, None],
                                         ctx)
                logits = logits[:, 0].astype(jnp.float32) + poison
                flags = self._guard_flags(logits, ctx.k_pools,
                                          ctx.v_pools, tables, lens)
                return (logits, ctx.k_pools, ctx.v_pools, flags)

            return decode, (
                self._params, self._k_pools, self._v_pools,
                jnp.zeros((cfg.max_num_seqs, cfg.max_pages_per_seq),
                          jnp.int32),
                jnp.zeros((cfg.max_num_seqs,), jnp.int32),
                jnp.zeros((cfg.max_num_seqs, 1), jnp.int32),
                jnp.zeros((cfg.max_num_seqs, 1), jnp.float32)), (1, 2), \
                self._guarded_out_shardings()

        def decode(params, k_pools, v_pools, tables, lens, tokens):
            ctx = PagedKVContext(k_pools, v_pools, tables, lens,
                                 cfg.page_size, "decode",
                                 quant=self._kv_quant)
            logits = self._run_model(params, tokens, lens[:, None], ctx)
            return (logits[:, 0].astype(jnp.float32),
                    ctx.k_pools, ctx.v_pools)

        return decode, (
            self._params, self._k_pools, self._v_pools,
            jnp.zeros((cfg.max_num_seqs, cfg.max_pages_per_seq),
                      jnp.int32),
            jnp.zeros((cfg.max_num_seqs,), jnp.int32),
            jnp.zeros((cfg.max_num_seqs, 1), jnp.int32)), (1, 2), \
            self._step_out_shardings()

    def _guarded_out_shardings(self):
        """Decode out_shardings with the guard-flag output appended
        (replicated, like the logits)."""
        base = self._step_out_shardings()
        if base is None:
            return None
        return (*base, self._repl_sharding)

    def _sampler_program(self, width):
        V = int(self._model.config.vocab_size)
        return sample_tokens, (
            jnp.zeros((width, V), jnp.float32),
            jnp.zeros((width,), jnp.int32),
            jnp.zeros((width,), jnp.int32),
            jnp.zeros((width,), jnp.float32),
            jnp.zeros((width,), jnp.int32),
            jnp.ones((width,), jnp.float32)), (), \
            (self._repl_sharding if self._mesh is not None else None)

    def _get_prefill(self, bucket):
        key = ("prefill", bucket)
        if key in self._compiled:
            return self._compiled[key]
        fn, example, donate, out_sh = self._prefill_program(bucket)
        return self._compile(key, fn, example, donate=donate,
                             out_shardings=out_sh)

    def _get_decode(self):
        key = ("decode",)
        if key in self._compiled:
            return self._compiled[key]
        fn, example, donate, out_sh = self._decode_program()
        return self._compile(key, fn, example, donate=donate,
                             out_shardings=out_sh)

    def _get_sampler(self, width):
        key = ("sample", width)
        if key in self._compiled:
            return self._compiled[key]
        fn, example, donate, out_sh = self._sampler_program(width)
        return self._compile(key, fn, example, donate=donate,
                             out_shardings=out_sh)

    def warmup(self):
        """Boot hook: compile — or load from the AOT program cache —
        EVERY program this engine can ever run (each prefill bucket,
        the decode step, both sampler widths).  Returns a summary dict;
        ``boot_ms`` is the cold-vs-warm number the router bench lane
        reports.  Idempotent."""
        t0 = time.perf_counter()
        for b in self.config.prefill_buckets:
            self._get_prefill(b)
        self._get_decode()
        self._get_sampler(1)
        self._get_sampler(self.config.max_num_seqs)
        return {
            "programs": len(self._compiled),
            "compiled": self.metrics.compile_count,
            "cache_loads": self.metrics.aot_cache_loads,
            "boot_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }

    # ---------------------------------------------------- self-audit
    @property
    def params_bytes(self):
        return sum(int(v.nbytes) for v in self._params.values())

    @property
    def kv_pool_bytes(self):
        """Total bytes of the paged K+V pools across all layers (the
        page budget, in bytes).  Quantized pools count codes AND their
        per-page scales — the honest narrow-storage number the
        hbm_budget/perfgate gates see."""
        return sum(int(leaf.nbytes) for leaf in
                   jax.tree_util.tree_leaves(self._k_pools)) + \
            sum(int(leaf.nbytes) for leaf in
                jax.tree_util.tree_leaves(self._v_pools))

    @property
    def kv_bytes_per_token(self):
        """Pool storage bytes per token of KV capacity across all
        layers — the serving-density metric the perfgate `quantization`
        target and the bench `--worker-quant` lane budget.  (Page 0 is
        reserved, but its bytes and its capacity cancel exactly, so
        this is total pool bytes over total page slots.)"""
        return self.kv_pool_bytes / (self.config.num_pages
                                     * self.config.page_size)

    @property
    def hbm_budget_bytes(self):
        """Documented per-program peak-HBM budget: weights + both the
        input and output aliases of the KV pools (XLA donates them, but
        the static estimate sees both live) + a fixed activations
        margin.  The decode/prefill programs must stay inside this —
        asserted by the shardlint self-audit gate in CI."""
        return self.params_bytes + 2 * self.kv_pool_bytes + (64 << 20)

    def audit_programs(self):
        """{name: ClosedJaxpr} for every program the engine will ever
        compile, traced (not compiled) from the same builders."""
        import jax
        progs = {}
        for b in self.config.prefill_buckets:
            fn, example, *_ = self._prefill_program(b)
            progs[f"prefill_{b}"] = jax.jit(fn).trace(*example).jaxpr
        fn, example, *_ = self._decode_program()
        progs["decode"] = jax.jit(fn).trace(*example).jaxpr
        for width in (1, self.config.max_num_seqs):
            fn, example, *_ = self._sampler_program(width)
            progs[f"sample_{width}"] = jax.jit(fn).trace(*example).jaxpr
        return progs

    def audit(self, config=None):
        """shardlint self-audit: run the SL-rule audit over every engine
        program against the documented compile + page budgets.  Returns
        a plain dict (JSON-able) — the CI gate asserts every program's
        ``within_budget`` and that the compile bound holds."""
        from paddle_tpu import analysis
        cfg = config or analysis.AuditConfig(
            hbm_budget_bytes=self.hbm_budget_bytes)
        out = {
            "compile_bound": self.config.compile_bound,
            "compiles_used": len(self._compiled),
            "pages_total": self.config.num_pages - 1,
            "params_mb": round(self.params_bytes / (1 << 20), 3),
            "kv_pool_mb": round(self.kv_pool_bytes / (1 << 20), 3),
            "kv_cache_dtype": self.config.kv_cache_dtype,
            "kv_bytes_per_token": round(self.kv_bytes_per_token, 3),
            "hbm_budget_mb": round(self.hbm_budget_bytes / (1 << 20), 3),
            "programs": {},
        }
        for name, jaxpr in self.audit_programs().items():
            findings, rep = analysis.audit_jaxpr(
                jaxpr, where=f"<serving {name}>", config=cfg)
            d = rep.to_dict()
            d["findings"] = [f.format() for f in findings]
            d["within_budget"] = not any(f.code == "SL301"
                                         for f in findings)
            out["programs"][name] = d
        return out

    def _compile(self, key, fn, example_args, donate=(),
                 out_shardings=None):
        """AOT compile + count: every program the engine will ever run
        passes through here, so `metrics.compile_count` is exact.

        `donate` names arg positions (the KV pools) XLA may alias
        in-place — without it every decode step materializes a second
        copy of the whole cache.  CPU's backend can't donate these and
        would warn on every call, so donation is accelerator-only.

        With an AOT program cache attached, the cache is consulted
        FIRST: a hit loads the persisted executable and records NO
        compile event anywhere (the warm-boot contract the router's
        zero-recompile acceptance test pins); a miss compiles as usual
        and persists the result for the next replica."""
        prog_name = "/".join(str(p) for p in key)
        if self._program_cache is not None:
            compiled = self._program_cache.load(self._program_fp,
                                               prog_name)
            if compiled is not None:
                with span("serving.aot_load", program=str(key),
                          fingerprint=self._program_fp):
                    pass
                self.metrics.note_aot_load()
                self._compiled[key] = compiled
                return compiled

        def _struct(a):
            if self._mesh is None:
                return jax.ShapeDtypeStruct(a.shape, a.dtype)
            sh = getattr(a, "sharding", None)
            if not isinstance(sh, jax.sharding.NamedSharding):
                sh = self._repl_sharding    # host-built example operand
            return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)

        shapes = jax.tree_util.tree_map(_struct, example_args)
        if jax.default_backend() == "cpu":
            donate = ()
        jit_kw = {"donate_argnums": donate}
        if out_shardings is not None:
            jit_kw["out_shardings"] = out_shardings
        t0 = time.perf_counter()
        with span("serving.compile", program=str(key)):
            compiled = jax.jit(fn, **jit_kw).lower(
                *shapes).compile()
        # the serving compile choke point reports into the same
        # recompile log as StaticFunction cache misses: one timeline
        # answers "what compiled, when, and against what bound" — record
        # BEFORE the storm check so an over-bound compile is the best-
        # documented event in the log, not a missing one; cache LAST so
        # a storm RuntimeError leaves no over-bound program behind that
        # a catch-and-retry caller could silently keep serving from
        note_aot_compile(
            prog_name,
            compile_ms=round((time.perf_counter() - t0) * 1e3, 3),
            cache_size=len(self._compiled) + 1,
            bound=self.config.compile_bound, engine=self._metrics_name)
        self.metrics.note_compile()
        self._compiled[key] = compiled
        if self._program_cache is not None:
            self._program_cache.store(self._program_fp, prog_name,
                                      compiled)
        return compiled
