"""SLO autoscaler — a hysteretic policy loop over the fleet's existing
telemetry, scaling the replica set through the router's own
park/unpark (drain + respawn-queue) machinery.

The policy reads EXACTLY the signals the fleet already exports — the
per-replica queue depth / page occupancy the ``serving_queue_depth``
and ``serving_page_occupancy`` gauges scrape (via
``ReplicaHandle.telemetry()``), plus an optional TTFT-p99 feed (the
traffic driver's per-class histograms, or a FleetMonitor heartbeat
aggregate) — and compares them against a declared :class:`SLO`.  It
never introspects engines.

**Hysteresis, so it never flaps**: a breach must persist for
``up_after`` consecutive observations before a scale-up, a clear for
``down_after`` (deliberately larger) before a scale-down, readings in
the dead band between ``queue_low`` and ``queue_high`` reset both
streaks, and every action starts a ``cooldown`` window during which no
further action fires.  The no-flap contract is pinned by
tests/test_traffic.py against an oscillating load.

**Scale-up rides the existing respawn queue**: ``router.unpark(i)``
re-queues a parked (spare) slot; the next ``router.step()`` boots it —
OUTSIDE the router lock, warm from the shared AOT program cache — so
admissions never stall behind an XLA compile.  Scale-down is
``router.park(i)``: a normal drain whose emptied slot is NOT
auto-respawned.  Reaction time (unpark → replica admitting) is
recorded in ``traffic_scaleup_reaction_seconds`` on the injected
clock — deterministic under the virtual-time driver, and the number
the perfgate ``traffic`` target pins.
"""
from __future__ import annotations

import threading
import time

from paddle_tpu.observability import span
from paddle_tpu.observability.metrics import (next_instance_label,
                                              registry)
from paddle_tpu.serving.metrics import _acquire_labels, _release_labels
from paddle_tpu.serving.router.replica import ReplicaState

__all__ = ["SLO", "AutoscalerConfig", "SLOAutoscaler"]


class SLO:
    """Declared service-level objectives (JSON-able, FaultPlan house
    style).  ``queue_high``/``queue_low`` bound the dead band on mean
    queue depth; ``occupancy_high`` guards the page pool; a TTFT p99
    bound applies when the caller wires a TTFT feed."""

    def __init__(self, ttft_p99_s=0.5, queue_high=6.0, queue_low=1.0,
                 occupancy_high=0.85):
        if queue_low >= queue_high:
            raise ValueError("queue_low must be < queue_high "
                             "(the hysteresis dead band)")
        if not 0.0 < occupancy_high <= 1.0:
            raise ValueError("occupancy_high must be in (0, 1]")
        self.ttft_p99_s = float(ttft_p99_s)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.occupancy_high = float(occupancy_high)

    def to_dict(self):
        return {"ttft_p99_s": self.ttft_p99_s,
                "queue_high": self.queue_high,
                "queue_low": self.queue_low,
                "occupancy_high": self.occupancy_high}

    @classmethod
    def from_dict(cls, d):
        return cls(d.get("ttft_p99_s", 0.5), d.get("queue_high", 6.0),
                   d.get("queue_low", 1.0),
                   d.get("occupancy_high", 0.85))

    def __repr__(self):
        return (f"SLO(ttft_p99_s={self.ttft_p99_s}, "
                f"queue=[{self.queue_low},{self.queue_high}], "
                f"occupancy_high={self.occupancy_high})")


class AutoscalerConfig:
    """Hysteresis knobs.  ``up_after`` < ``down_after`` by default:
    scaling up is cheap (warm boot) and protects the SLO; scaling down
    only saves capacity, so it must be much surer."""

    def __init__(self, min_replicas=1, max_replicas=None, up_after=2,
                 down_after=8, cooldown=4):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if up_after < 1 or down_after < 1 or cooldown < 0:
            raise ValueError("up_after/down_after >= 1, cooldown >= 0")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas) \
            if max_replicas is not None else None
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self.cooldown = int(cooldown)

    def to_dict(self):
        return {"min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "up_after": self.up_after,
                "down_after": self.down_after,
                "cooldown": self.cooldown}

    @classmethod
    def from_dict(cls, d):
        return cls(d.get("min_replicas", 1), d.get("max_replicas"),
                   d.get("up_after", 2), d.get("down_after", 8),
                   d.get("cooldown", 4))


class SLOAutoscaler:
    """The policy loop (module docstring has the semantics).

    Drive it either by calling :meth:`observe` once per scheduling
    quantum (the traffic driver's ``on_tick`` slot — policy and load
    then share one deterministic timeline) or via the
    :meth:`start`/:meth:`stop` background thread for a live fleet.
    `ttft_p99_s_fn` is an optional zero-arg callable returning the
    current TTFT p99 in seconds (None = signal absent).
    """

    def __init__(self, router, slo=None, config=None,
                 clock=time.perf_counter, ttft_p99_s_fn=None,
                 name=None):
        self.router = router
        self.slo = slo or SLO()
        self.config = config or AutoscalerConfig()
        self.clock = clock
        self.ttft_p99_s_fn = ttft_p99_s_fn
        self.name = name or next_instance_label("autoscaler")
        self.labels = {"autoscaler": self.name}
        _acquire_labels(self.labels)
        self._released = False
        reg = registry()
        self._up_counter = reg.counter(
            "traffic_scale_up_total", labels=self.labels,
            help="replicas unparked by the SLO autoscaler")
        self._down_counter = reg.counter(
            "traffic_scale_down_total", labels=self.labels,
            help="replicas parked by the SLO autoscaler")
        self._active_gauge = reg.gauge(
            "traffic_replicas_active", labels=self.labels,
            help="replicas active in rotation, autoscaler view")
        self._reaction_hist = reg.histogram(
            "traffic_scaleup_reaction_seconds", labels=self.labels,
            help="unpark decision to replica-admitting latency")
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self._thread = None
        self._breach_streak = 0
        self._clear_streak = 0
        self._cooldown = 0
        self._pending_up = {}        # replica index -> decision time
        self.scale_ups = 0
        self.scale_downs = 0
        self.reaction_times = []     # seconds, per completed scale-up
        self.observations = 0

    # --------------------------------------------------------- signals
    def _read_signals(self):
        """(active_handles, parked_indices, mean_queue, max_occupancy)
        — all from router-public telemetry."""
        replicas = self.router.replicas
        parked = self.router.parked
        active = [h for h in replicas
                  if h.state is ReplicaState.ACTIVE
                  and h.index not in parked]
        if not active:
            return active, parked, float("inf"), 1.0
        tele = [h.telemetry() for h in active]
        mean_q = sum(t["queue_depth"] + t["running"]
                     for t in tele) / len(tele)
        max_occ = max(t["page_occupancy"] for t in tele)
        return active, parked, mean_q, max_occ

    # --------------------------------------------------------- observe
    def observe(self):
        """One policy evaluation; returns ``"scale_up"``,
        ``"scale_down"``, or None.  Deterministic given the telemetry
        sequence — no wall clock, no RNG."""
        # user callbacks (clock, TTFT probe) run OUTSIDE _lock: either
        # may block or re-enter the autoscaler (racelint RL103)
        now = self.clock()
        p99 = self.ttft_p99_s_fn() if self.ttft_p99_s_fn else None
        with self._lock:
            self.observations += 1
            active, parked, mean_q, max_occ = self._read_signals()
            self._active_gauge.set(len(active))
            # close out completed scale-ups (reaction-time record)
            for idx in list(self._pending_up):
                h = next((r for r in self.router.replicas
                          if r.index == idx), None)
                if h is not None and h.admitting:
                    dt = now - self._pending_up.pop(idx)
                    self.reaction_times.append(dt)
                    self._reaction_hist.observe(dt)
            breach = (mean_q > self.slo.queue_high
                      or max_occ > self.slo.occupancy_high
                      or (p99 is not None
                          and p99 > self.slo.ttft_p99_s))
            clear = (mean_q < self.slo.queue_low
                     and max_occ <= self.slo.occupancy_high
                     and (p99 is None or p99 <= self.slo.ttft_p99_s))
            if breach:
                self._breach_streak += 1
                self._clear_streak = 0
            elif clear:
                self._clear_streak += 1
                self._breach_streak = 0
            else:
                # dead band: neither streak may grow — this is the
                # hysteresis that keeps an oscillating load from
                # flapping the fleet
                self._breach_streak = 0
                self._clear_streak = 0
            if self._cooldown > 0:
                self._cooldown -= 1
                return None
            if (self._breach_streak >= self.config.up_after
                    and parked
                    and (self.config.max_replicas is None
                         or len(active) < self.config.max_replicas)):
                idx = min(parked)
                self.router.unpark(idx)
                self._pending_up[idx] = now
                self.scale_ups += 1
                self._up_counter.inc()
                self._breach_streak = 0
                self._cooldown = self.config.cooldown
                with span("serving.traffic.scale_up", replica=idx,
                          mean_queue=round(mean_q, 2),
                          occupancy=round(max_occ, 3)):
                    pass
                return "scale_up"
            if (self._clear_streak >= self.config.down_after
                    and len(active) > self.config.min_replicas
                    and not self._pending_up):
                # deterministic victim: the highest-index active
                # replica (same tie-break direction as routing scores)
                idx = max(h.index for h in active)
                self.router.park(idx)
                self.scale_downs += 1
                self._down_counter.inc()
                self._clear_streak = 0
                self._cooldown = self.config.cooldown
                with span("serving.traffic.scale_down", replica=idx,
                          mean_queue=round(mean_q, 2)):
                    pass
                return "scale_down"
            return None

    # --------------------------------------------------- background loop
    def start(self, interval_s=0.05):
        """Spawn the live policy loop (daemon thread; idempotent).  Use
        only outside the virtual-time driver — under the driver, slot
        :meth:`observe` into ``on_tick`` instead."""
        with self._lock:
            if self._thread is not None:
                return self._thread
            self._stop_event.clear()
            t = threading.Thread(target=self._loop,
                                 args=(float(interval_s),),
                                 name=f"{self.name}.loop", daemon=True)
            self._thread = t
        t.start()
        return t

    def _loop(self, interval_s):
        while not self._stop_event.is_set():
            try:
                self.observe()
            except Exception as e:
                # the policy loop must survive a bad observation (a
                # replica mid-respawn can race telemetry reads) —
                # record and keep watching, never die silently
                with span("serving.traffic.autoscaler_error",
                          exc=type(e).__name__):
                    pass
            self._stop_event.wait(interval_s)

    def stop(self):
        """Stop and join the loop (no-op when not running)."""
        self._stop_event.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30.0)

    # ----------------------------------------------------------- report
    def snapshot(self):
        with self._lock:
            return {
                "observations": self.observations,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "reaction_times_s": [round(t, 6)
                                     for t in self.reaction_times],
                "pending_scale_ups": len(self._pending_up),
                "slo": self.slo.to_dict(),
                "config": self.config.to_dict(),
            }

    def release(self):
        """Stop the loop and drop the registry claim (idempotent)."""
        self.stop()
        if self._released:
            return
        self._released = True
        _release_labels(self.labels)
