"""Workload models — a JSON-able :class:`TrafficSpec` compiled into a
deterministic request trace.

The spec is DATA in the :class:`~paddle_tpu.resilience.FaultPlan` house
style (``to_dict`` / ``from_dict`` round-trip exactly), and compilation
is a pure function of ``(spec, spec.seed)``: the same spec always
yields a byte-identical trace (:func:`trace_digest` is the proof
handle).  Nothing here reads wall clock or global RNG state — one
``random.Random(seed)`` drives every draw in a fixed order, so a trace
replayed on another host, another day, or inside the capacity probe's
binary search is THE SAME workload.

A spec describes, independently:

- the **arrival process**: ``{"kind": "poisson", "rate_qps": R}`` or an
  on/off burst model ``{"kind": "onoff", "base_qps": B,
  "burst_qps": S, "period_s": P, "duty": D}`` (the first ``D`` fraction
  of every period runs at ``burst_qps``);
- the **prompt / output length mixtures**: weighted uniform ranges
  ``[[weight, lo, hi], ...]`` (inclusive bounds, token counts);
- the **shared-prefix ratio**: a fraction of requests opens with one
  spec-wide common prefix (the prefix-caching workload knob);
- the **deadline classes**: named SLO tiers (:class:`DeadlineClass`)
  with a TTFT SLO, an optional enforced engine deadline, and a mixture
  weight;
- an optional **fault plan** (``spec.fault_plan``, FaultPlan dict
  schema): the driver arms it for the run, so a chaos-composed traffic
  run is one JSON file.
"""
from __future__ import annotations

import hashlib
import json
import random

__all__ = ["DeadlineClass", "TraceRequest", "TrafficSpec",
           "compile_trace", "trace_digest"]


class DeadlineClass:
    """One SLO tier: requests of this class declare a TTFT SLO (the
    goodput bar the driver accounts against) and optionally an ENFORCED
    engine deadline (``SamplingParams.deadline_s`` — the engine expires
    the request past it).  ``weight`` is the mixture weight."""

    def __init__(self, name, ttft_slo_s, deadline_s=None, weight=1.0):
        if ttft_slo_s <= 0:
            raise ValueError("ttft_slo_s must be positive")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be positive (or None)")
        if weight <= 0:
            raise ValueError("weight must be positive")
        self.name = str(name)
        self.ttft_slo_s = float(ttft_slo_s)
        self.deadline_s = float(deadline_s) if deadline_s is not None \
            else None
        self.weight = float(weight)

    def to_dict(self):
        return {"name": self.name, "ttft_slo_s": self.ttft_slo_s,
                "deadline_s": self.deadline_s, "weight": self.weight}

    @classmethod
    def from_dict(cls, d):
        return cls(d["name"], d["ttft_slo_s"], d.get("deadline_s"),
                   d.get("weight", 1.0))

    def __repr__(self):
        return (f"DeadlineClass({self.name!r}, "
                f"ttft_slo_s={self.ttft_slo_s}, "
                f"deadline_s={self.deadline_s}, weight={self.weight})")


def _check_mixture(mix, what):
    out = []
    for row in mix:
        w, lo, hi = row
        if w <= 0 or lo < 1 or hi < lo:
            raise ValueError(f"bad {what} mixture row {row!r} "
                             f"(want [weight>0, lo>=1, hi>=lo])")
        out.append([float(w), int(lo), int(hi)])
    if not out:
        raise ValueError(f"{what} mixture must have at least one row")
    return out


class TrafficSpec:
    """The workload model (module docstring has the schema).  A spec is
    immutable in spirit: derive variants with :meth:`with_rate` instead
    of mutating — the capacity probe's binary search depends on it."""

    ARRIVAL_KINDS = ("poisson", "onoff")

    def __init__(self, name="traffic", seed=0, arrival=None,
                 duration_s=2.0, prompt_len=((1.0, 4, 12),),
                 output_tokens=((1.0, 4, 8),), shared_prefix=None,
                 classes=(), vocab=(1, 256), temperature=0.8,
                 top_p=0.95, fault_plan=None):
        self.name = str(name)
        self.seed = int(seed)
        self.arrival = dict(arrival or {"kind": "poisson",
                                        "rate_qps": 8.0})
        kind = self.arrival.get("kind")
        if kind not in self.ARRIVAL_KINDS:
            raise ValueError(f"unknown arrival kind {kind!r}; one of "
                             f"{self.ARRIVAL_KINDS}")
        if kind == "poisson" and self.arrival.get("rate_qps", 0) <= 0:
            raise ValueError("poisson arrival needs rate_qps > 0")
        if kind == "onoff":
            for k in ("base_qps", "burst_qps", "period_s"):
                if self.arrival.get(k, 0) <= 0:
                    raise ValueError(f"onoff arrival needs {k} > 0")
            duty = self.arrival.setdefault("duty", 0.25)
            if not 0.0 < duty < 1.0:
                raise ValueError("onoff duty must be in (0, 1)")
        if duration_s <= 0:
            raise ValueError("duration_s must be positive")
        self.duration_s = float(duration_s)
        self.prompt_len = _check_mixture(prompt_len, "prompt_len")
        self.output_tokens = _check_mixture(output_tokens,
                                            "output_tokens")
        self.shared_prefix = dict(shared_prefix) if shared_prefix \
            else {"ratio": 0.0, "length": 0}
        ratio = self.shared_prefix.get("ratio", 0.0)
        if not 0.0 <= ratio <= 1.0:
            raise ValueError("shared_prefix ratio must be in [0, 1]")
        self.classes = [c if isinstance(c, DeadlineClass)
                        else DeadlineClass.from_dict(c)
                        for c in classes] or \
            [DeadlineClass("default", ttft_slo_s=1.0)]
        lo, hi = vocab
        if not 0 <= lo < hi:
            raise ValueError("vocab must be (lo, hi) with 0 <= lo < hi")
        self.vocab = (int(lo), int(hi))
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.fault_plan = dict(fault_plan) if fault_plan else None

    # ------------------------------------------------------- derivation
    def with_rate(self, rate_qps, duration_s=None):
        """A copy of this spec offered at a flat Poisson `rate_qps` —
        what the capacity probe sweeps.  Same seed: the probe varies
        ONLY the offered load."""
        d = self.to_dict()
        d["arrival"] = {"kind": "poisson", "rate_qps": float(rate_qps)}
        if duration_s is not None:
            d["duration_s"] = float(duration_s)
        return TrafficSpec.from_dict(d)

    # ---------------------------------------------------------- JSON
    def to_dict(self):
        return {
            "name": self.name, "seed": self.seed,
            "arrival": dict(self.arrival),
            "duration_s": self.duration_s,
            "prompt_len": [list(r) for r in self.prompt_len],
            "output_tokens": [list(r) for r in self.output_tokens],
            "shared_prefix": dict(self.shared_prefix),
            "classes": [c.to_dict() for c in self.classes],
            "vocab": list(self.vocab),
            "temperature": self.temperature, "top_p": self.top_p,
            "fault_plan": dict(self.fault_plan)
            if self.fault_plan else None,
        }

    @classmethod
    def from_dict(cls, d):
        return cls(name=d.get("name", "traffic"), seed=d.get("seed", 0),
                   arrival=d.get("arrival"),
                   duration_s=d.get("duration_s", 2.0),
                   prompt_len=d.get("prompt_len", ((1.0, 4, 12),)),
                   output_tokens=d.get("output_tokens", ((1.0, 4, 8),)),
                   shared_prefix=d.get("shared_prefix"),
                   classes=d.get("classes", ()),
                   vocab=tuple(d.get("vocab", (1, 256))),
                   temperature=d.get("temperature", 0.8),
                   top_p=d.get("top_p", 0.95),
                   fault_plan=d.get("fault_plan"))

    def __repr__(self):
        return (f"TrafficSpec({self.name!r}, seed={self.seed}, "
                f"{self.arrival}, {self.duration_s}s, "
                f"{len(self.classes)} classes)")


class TraceRequest:
    """One compiled arrival: WHEN (virtual seconds from run start),
    WHAT (prompt tokens + sampling), and the SLO class it is accounted
    under."""

    __slots__ = ("index", "arrive_s", "prompt", "max_new_tokens",
                 "cls", "ttft_slo_s", "deadline_s", "seed",
                 "temperature", "top_p", "shared_prefix")

    def __init__(self, index, arrive_s, prompt, max_new_tokens, cls,
                 ttft_slo_s, deadline_s, seed, temperature, top_p,
                 shared_prefix):
        self.index = int(index)
        self.arrive_s = float(arrive_s)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.cls = str(cls)
        self.ttft_slo_s = float(ttft_slo_s)
        self.deadline_s = deadline_s
        self.seed = int(seed)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.shared_prefix = bool(shared_prefix)

    def sampling_params(self):
        from paddle_tpu.serving.request import SamplingParams
        return SamplingParams(max_new_tokens=self.max_new_tokens,
                              temperature=self.temperature,
                              top_p=self.top_p, seed=self.seed,
                              deadline_s=self.deadline_s)

    def to_dict(self):
        return {"index": self.index,
                "arrive_s": round(self.arrive_s, 9),
                "prompt": list(self.prompt),
                "max_new_tokens": self.max_new_tokens,
                "cls": self.cls, "ttft_slo_s": self.ttft_slo_s,
                "deadline_s": self.deadline_s, "seed": self.seed,
                "temperature": self.temperature, "top_p": self.top_p,
                "shared_prefix": self.shared_prefix}

    def __repr__(self):
        return (f"TraceRequest(#{self.index} @{self.arrive_s:.3f}s, "
                f"{len(self.prompt)}+{self.max_new_tokens} tok, "
                f"cls={self.cls})")


def _pick_range(rng, mixture):
    total = sum(r[0] for r in mixture)
    x = rng.random() * total
    for w, lo, hi in mixture:
        x -= w
        if x <= 0:
            return rng.randint(lo, hi)
    return mixture[-1][1]


def _pick_class(rng, classes):
    total = sum(c.weight for c in classes)
    x = rng.random() * total
    for c in classes:
        x -= c.weight
        if x <= 0:
            return c
    return classes[-1]


def _arrival_times(rng, spec):
    """Arrival instants in [0, duration_s) — exponential gaps at the
    instantaneous rate (for ``onoff``, the rate in force at the moment
    the gap starts; deterministic, no thinning rejection loop)."""
    arr = spec.arrival
    kind = arr["kind"]
    t, out = 0.0, []
    while True:
        if kind == "poisson":
            rate = float(arr["rate_qps"])
        else:
            period = float(arr["period_s"])
            burst_until = period * float(arr["duty"])
            rate = float(arr["burst_qps"]) \
                if (t % period) < burst_until else float(arr["base_qps"])
        t += rng.expovariate(rate)
        if t >= spec.duration_s:
            return out
        out.append(t)


def compile_trace(spec, count=None, start_index=0):
    """Compile `spec` into its deterministic request trace.

    Same spec ⇒ byte-identical trace (assert with :func:`trace_digest`).
    `count` overrides the arrival process with exactly-`count` requests
    at the process's arrival instants (cycling past the duration when
    needed) — the surge injector and unit tests use it; normal runs
    leave it None.
    """
    rng = random.Random(spec.seed * 1000003 + start_index)
    lo, hi = spec.vocab
    prefix_len = int(spec.shared_prefix.get("length", 0))
    prefix_ratio = float(spec.shared_prefix.get("ratio", 0.0))
    prefix = [rng.randrange(lo, hi) for _ in range(prefix_len)]
    times = _arrival_times(rng, spec)
    if count is not None:
        base, times = list(times) or [0.0], []
        for i in range(int(count)):
            cycle, j = divmod(i, len(base))
            times.append(base[j] + cycle * spec.duration_s)
    out = []
    for i, arrive_s in enumerate(times):
        idx = start_index + i
        c = _pick_class(rng, spec.classes)
        plen = _pick_range(rng, spec.prompt_len)
        otok = _pick_range(rng, spec.output_tokens)
        shared = prefix_len > 0 and rng.random() < prefix_ratio
        body_len = max(1, plen - prefix_len) if shared else plen
        prompt = (prefix if shared else []) \
            + [rng.randrange(lo, hi) for _ in range(body_len)]
        out.append(TraceRequest(
            idx, arrive_s, prompt, otok, c.name, c.ttft_slo_s,
            c.deadline_s, seed=spec.seed * 7919 + idx,
            temperature=spec.temperature, top_p=spec.top_p,
            shared_prefix=shared))
    return out


def trace_digest(trace):
    """sha256 over the canonical JSON of the trace — the byte-identity
    proof handle two same-seed compilations must agree on."""
    payload = json.dumps([r.to_dict() for r in trace],
                         sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()
