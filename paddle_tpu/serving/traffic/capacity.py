"""Capacity probe — binary-search the max sustained QPS per replica
count, reported as a JSON-able :class:`CapacityReport`.

This is the Gemma-on-TPU serving-comparison evidence style (PAPERS.md):
"max sustained QPS at p99 TTFT ≤ X" per replica count, where
*sustained* means the offered load finished with goodput ≥
``goodput_min`` AND TTFT p99 within the SLO.  Because every probe run
rides the deterministic virtual-time driver, the whole binary search is
replay-stable: same spec + same factory ⇒ the same report, byte for
byte — which is what lets perfgate pin capacity numbers and lets a
BENCH report compare replica counts honestly.

Chaos composes: hand ``probe_capacity`` a ``fault_plan`` (or put one on
the spec) and the same search runs under injected ``rank_kill`` /
``wedge`` faults — the goodput-within-budget acceptance of
docs/resilience.md's chaos proofs, turned into capacity-planning
numbers.

Render a report with :meth:`CapacityReport.render`, or from a dump via
``tools/obs_report.py --capacity`` (the report rides
``observability.export.dump_jsonl(capacities=[...])``).
"""
from __future__ import annotations

from paddle_tpu.serving.traffic.driver import TrafficDriver, VirtualClock
from paddle_tpu.serving.traffic.workload import TrafficSpec

__all__ = ["CapacityReport", "probe_capacity", "run_traffic"]


class CapacityReport:
    """Per-replica-count capacity rows + the search parameters that
    produced them (FaultPlan-style ``to_dict``/``from_dict``)."""

    def __init__(self, name, slo_ttft_s, goodput_min, rows,
                 fault_plan=None):
        self.name = str(name)
        self.slo_ttft_s = float(slo_ttft_s)
        self.goodput_min = float(goodput_min)
        self.rows = [dict(r) for r in rows]
        self.fault_plan = dict(fault_plan) if fault_plan else None

    def max_qps(self, replicas):
        for r in self.rows:
            if r["replicas"] == replicas:
                return r["max_qps"]
        raise KeyError(f"no capacity row for {replicas} replicas")

    def to_dict(self):
        return {"name": self.name, "slo_ttft_s": self.slo_ttft_s,
                "goodput_min": self.goodput_min,
                "rows": [dict(r) for r in self.rows],
                "fault_plan": dict(self.fault_plan)
                if self.fault_plan else None}

    @classmethod
    def from_dict(cls, d):
        return cls(d.get("name", "capacity"), d["slo_ttft_s"],
                   d.get("goodput_min", 0.95), d.get("rows", ()),
                   d.get("fault_plan"))

    def render(self):
        """Human table (the ``obs_report --capacity`` view)."""
        lines = [
            f"== capacity {self.name} — sustained QPS at p99 TTFT <= "
            f"{self.slo_ttft_s * 1e3:.0f}ms, goodput >= "
            f"{100 * self.goodput_min:.0f}%"
            + (f", under fault plan "
               f"{self.fault_plan.get('name', '?')}"
               if self.fault_plan else "") + " " + "=" * 8,
            f"  {'replicas':>8s} {'max QPS':>9s} {'goodput':>8s} "
            f"{'p99 TTFT ms':>12s} {'probes':>7s}",
        ]
        for r in self.rows:
            gp = r.get("goodput_frac")
            p99 = r.get("ttft_p99_ms")
            gp_s = f"{100 * gp:>7.1f}%" if gp is not None else f"{'-':>8s}"
            p99_s = f"{p99:>12.1f}" if p99 is not None else f"{'-':>12s}"
            lines.append(f"  {r['replicas']:>8d} {r['max_qps']:>9.2f} "
                         f"{gp_s} {p99_s} {r.get('probes', 0):>7d}")
        return "\n".join(lines)

    def __repr__(self):
        return (f"CapacityReport({self.name!r}, "
                f"{len(self.rows)} replica counts, "
                f"slo={self.slo_ttft_s}s)")


def run_traffic(router_factory, spec, replicas, quantum_s=0.005,
                on_tick_factory=None, **driver_kw):
    """One fresh deterministic traffic run: new VirtualClock, new
    router from ``router_factory(replicas, clock)``, full trace replay,
    clean shutdown.  Returns the driver's report dict."""
    clock = VirtualClock()
    router = router_factory(replicas, clock)
    driver = TrafficDriver(router, spec, clock, quantum_s=quantum_s,
                           **driver_kw)
    if on_tick_factory is not None:
        driver.on_tick = on_tick_factory(router, clock, driver)
    try:
        return driver.run()
    finally:
        driver.release()
        router.shutdown()


def _sustained(report, slo_ttft_s, goodput_min):
    p99 = report.get("ttft_p99_ms")
    return (report["goodput_frac"] >= goodput_min
            and p99 is not None and p99 <= slo_ttft_s * 1e3)


def probe_capacity(router_factory, spec, slo_ttft_s=0.5,
                   replica_counts=(1, 2), qps_lo=0.5, qps_hi=64.0,
                   iters=5, goodput_min=0.95, quantum_s=0.005,
                   fault_plan=None, name=None):
    """Binary-search max sustained QPS for each replica count.

    ``router_factory(num_replicas, clock)`` must return a fresh
    :class:`~paddle_tpu.serving.router.Router` built ON that clock
    (share one AOT cache dir across calls so probes boot warm).  The
    search brackets [`qps_lo`, `qps_hi`]: a load unsustainable at
    `qps_lo` reports ``max_qps 0.0``; one sustainable at `qps_hi`
    reports `qps_hi` (widen the bracket for bigger fleets).  With
    `fault_plan` (or ``spec.fault_plan``) every probe runs under the
    injected faults — capacity under chaos.
    """
    if not isinstance(spec, TrafficSpec):
        spec = TrafficSpec.from_dict(spec)
    if fault_plan is not None:
        d = spec.to_dict()
        d["fault_plan"] = dict(fault_plan)
        spec = TrafficSpec.from_dict(d)
    rows = []
    for n in replica_counts:
        probes = 0

        def measure(qps):
            nonlocal probes
            probes += 1
            return run_traffic(router_factory, spec.with_rate(qps), n,
                               quantum_s=quantum_s,
                               name=f"{spec.name}-cap{n}r")

        lo_rep = measure(qps_lo)
        if not _sustained(lo_rep, slo_ttft_s, goodput_min):
            rows.append({"replicas": int(n), "max_qps": 0.0,
                         "goodput_frac": lo_rep["goodput_frac"],
                         "ttft_p99_ms": lo_rep.get("ttft_p99_ms"),
                         "probes": probes})
            continue
        hi_rep = measure(qps_hi)
        if _sustained(hi_rep, slo_ttft_s, goodput_min):
            rows.append({"replicas": int(n), "max_qps": float(qps_hi),
                         "goodput_frac": hi_rep["goodput_frac"],
                         "ttft_p99_ms": hi_rep.get("ttft_p99_ms"),
                         "probes": probes})
            continue
        lo, hi = float(qps_lo), float(qps_hi)
        best = lo_rep
        for _ in range(int(iters)):
            mid = (lo + hi) / 2.0
            rep = measure(mid)
            if _sustained(rep, slo_ttft_s, goodput_min):
                lo, best = mid, rep
            else:
                hi = mid
        rows.append({"replicas": int(n), "max_qps": round(lo, 3),
                     "goodput_frac": best["goodput_frac"],
                     "ttft_p99_ms": best.get("ttft_p99_ms"),
                     "probes": probes})
    return CapacityReport(name or f"{spec.name}-capacity", slo_ttft_s,
                          goodput_min, rows,
                          fault_plan=spec.fault_plan)
