"""Open-loop trace replay against a :class:`~paddle_tpu.serving.router.
Router` on a virtual clock.

**Open-loop** means arrivals come from the compiled trace's schedule,
never from the fleet's completion rate — the generator keeps offering
load at the spec's QPS even while queues build, which is the only
arrival discipline that can measure an SLO (a closed loop self-throttles
and hides saturation).

**Virtual time** makes the whole measurement deterministic: the driver
owns a :class:`VirtualClock` that advances by exactly ``quantum_s``
per fleet step, and the same clock is injected into the router and
every engine (``Router(clock=...)`` → ``EngineMetrics.clock``), so
``arrive_t``, deadline TTLs, TTFT histograms, and goodput counters are
pure functions of the spec — two same-seed runs produce identical
metric snapshots (asserted in tests/test_traffic.py).  One step
modeling one quantum is the service-time model; wall time never enters.

Outcomes land in the observability registry under a
``traffic=<name>`` label: ``traffic_goodput_total``,
``traffic_slo_violation_total``, per-class
``traffic_ttft_seconds{class=...}`` histograms (same instruments the
Prometheus exporter scrapes).  A request counts toward GOODPUT only if
it finished normally, with every expected token, within its class's
TTFT SLO; everything else — deadline expiry, SLO-late first tokens,
lost admissions — is an SLO violation.

Chaos composes by construction: ``spec.fault_plan`` (FaultPlan dict)
is armed around the run, and each driver tick polls the
``serving.traffic.tick`` fault site — a ``qps_surge`` spec there
injects ``payload["requests"]`` extra arrivals mid-run (compiled from
the same seed, so even the surge is replay-identical).
"""
from __future__ import annotations

import threading

from paddle_tpu.observability.metrics import (next_instance_label,
                                              registry)
from paddle_tpu.resilience import faultinject
from paddle_tpu.serving.metrics import _acquire_labels, _release_labels
from paddle_tpu.serving.scheduler import AdmissionRejected
from paddle_tpu.serving.traffic.workload import (TrafficSpec,
                                                 compile_trace)

__all__ = ["VirtualClock", "TrafficDriver", "TrafficMetrics"]

_SURGE_BASE = 1 << 20   # surge request indices: disjoint from any trace


class VirtualClock:
    """A deterministic, caller-advanced clock — drop-in for
    ``time.perf_counter`` wherever a clock is injectable
    (``EngineMetrics.clock``, ``Router(clock=...)``).  Monotonic by
    construction: only :meth:`advance` moves it, forward only."""

    def __init__(self, start=0.0):
        self._now = float(start)
        self._lock = threading.Lock()

    def __call__(self):
        with self._lock:
            return self._now

    @property
    def now(self):
        return self()

    def advance(self, dt):
        if dt < 0:
            raise ValueError("a clock only advances")
        with self._lock:
            self._now += float(dt)
            return self._now

    def __repr__(self):
        return f"VirtualClock({self():.6f}s)"


class TrafficMetrics:
    """The traffic run's registry instruments, all labeled
    ``traffic=<name>`` (+ ``class=`` on the per-class TTFT
    histograms).  Same refcounted label lifecycle as
    :class:`~paddle_tpu.serving.metrics.EngineMetrics`: instruments are
    dropped when the last same-named owner releases."""

    def __init__(self, name=None):
        self.name = name or next_instance_label("traffic")
        self.labels = {"traffic": self.name}
        reg = registry()
        _acquire_labels(self.labels)
        self._released = False
        self._class_labels = {}
        self.offered = reg.counter(
            "traffic_offered_total", labels=self.labels,
            help="requests offered by the load generator")
        self.goodput = reg.counter(
            "traffic_goodput_total", labels=self.labels,
            help="requests completed in full within their TTFT SLO")
        self.slo_violation = reg.counter(
            "traffic_slo_violation_total", labels=self.labels,
            help="requests that missed their SLO (late, expired, lost)")
        self.expired = reg.counter(
            "traffic_expired_total", labels=self.labels,
            help="requests expired by engine deadline enforcement")
        self.admission_retry = reg.counter(
            "traffic_admission_retry_total", labels=self.labels,
            help="admission attempts deferred by fleet backpressure")
        self.surge_injected = reg.counter(
            "traffic_surge_injected_total", labels=self.labels,
            help="extra requests injected by a qps_surge fault")
        self.inflight = reg.gauge(
            "traffic_inflight", labels=self.labels,
            help="requests admitted and not yet finished")
        self.itl = reg.histogram(
            "traffic_itl_seconds", labels=self.labels,
            help="inter-token latency under the traffic run (virtual)")

    def class_ttft(self, cls):
        labels = self._class_labels.get(cls)
        if labels is None:
            labels = dict(self.labels)
            labels["class"] = cls
            _acquire_labels(labels)
            self._class_labels[cls] = labels
        return registry().histogram(
            "traffic_ttft_seconds", labels=labels,
            help="time to first token by deadline class (virtual)")

    def release(self):
        if self._released:
            return
        self._released = True
        for labels in self._class_labels.values():
            _release_labels(labels)
        _release_labels(self.labels)


class _Flight:
    """Driver-side shadow of one offered request."""

    __slots__ = ("treq", "rid", "first_t", "last_t", "tokens",
                 "finished", "reason")

    def __init__(self, treq):
        self.treq = treq
        self.rid = None
        self.first_t = None
        self.last_t = None
        self.tokens = 0
        self.finished = False
        self.reason = None


class TrafficDriver:
    """Replay one :class:`TrafficSpec` against `router` (module
    docstring has the semantics).  The driver OWNS stepping: it calls
    ``router.step()`` once per quantum — don't also run the router's
    background loop, or service time stops being modeled.

    `clock` must be the same :class:`VirtualClock` the router (and
    through it every engine) was built with; `on_tick(driver)` is an
    optional per-quantum hook — the SLO autoscaler's ``observe`` slots
    in here so policy and load share one timeline.
    """

    def __init__(self, router, spec, clock, quantum_s=0.005, name=None,
                 max_ticks=250000, stall_ticks=4096, on_tick=None):
        if not isinstance(spec, TrafficSpec):
            spec = TrafficSpec.from_dict(spec)
        self.router = router
        self.spec = spec
        self.clock = clock
        self.quantum_s = float(quantum_s)
        self.max_ticks = int(max_ticks)
        self.stall_ticks = int(stall_ticks)
        self.on_tick = on_tick
        self.metrics = TrafficMetrics(name or spec.name)
        self._lock = threading.Lock()
        self._flights = {}          # rid -> _Flight
        self._done_log = []         # (flight, ttft, complete)
        self.ticks = 0
        self._surge_fired = 0

    # --------------------------------------------------------- streaming
    def _stream_for(self, fl):
        clock = self.clock
        lock = self._lock
        itl = self.metrics.itl

        def _stream(rid, tok, fin):
            now = clock()
            with lock:
                if tok is not None:
                    fl.tokens += 1
                    if fl.first_t is None:
                        fl.first_t = now
                    elif fl.last_t is not None:
                        itl.observe(now - fl.last_t)
                    fl.last_t = now
                if fin:
                    fl.finished = True

        return _stream

    # --------------------------------------------------------- admission
    def _try_admit(self, fl):
        """One admission attempt; True when placed.  Rejections are
        backpressure, not failures — the flight retries next tick with
        its ORIGINAL arrival time still the TTFT baseline (queueing
        while rejected is latency the SLO must see)."""
        try:
            rid = self.router.add_request(
                fl.treq.prompt, fl.treq.sampling_params(),
                stream=self._stream_for(fl))
        except AdmissionRejected:
            self.metrics.admission_retry.inc()
            return False
        with self._lock:
            fl.rid = rid
            self._flights[rid] = fl
        return True

    def _surge(self, spec_hit):
        n = int(spec_hit.payload.get("requests", 8))
        extra = compile_trace(
            self.spec, count=n,
            start_index=_SURGE_BASE + self._surge_fired * 4096)
        self._surge_fired += 1
        now = self.clock()
        for treq in extra:
            treq.arrive_s = now
        self.metrics.surge_injected.inc(n)
        return [_Flight(t) for t in extra]

    # -------------------------------------------------------------- run
    def run(self):
        """Drive the trace to completion; returns the report dict (and
        leaves the same numbers in the registry instruments)."""
        plan = None
        if self.spec.fault_plan and faultinject.active_plan() is None:
            plan = faultinject.FaultPlan.from_dict(self.spec.fault_plan)
        if plan is not None:
            with faultinject.FaultInjector(plan):
                return self._run()
        return self._run()

    def _run(self):
        trace = compile_trace(self.spec)
        self.metrics.offered.inc(len(trace))
        waiting = [_Flight(t) for t in trace]   # arrival order
        retry = []
        idle = 0
        while waiting or retry or self._flights:
            if self.ticks >= self.max_ticks:
                raise RuntimeError(
                    f"traffic run exceeded max_ticks={self.max_ticks} "
                    f"({len(self._flights)} in flight, "
                    f"{len(waiting) + len(retry)} unadmitted)")
            spec_hit = faultinject.fire("serving.traffic.tick",
                                        tick=self.ticks)
            if spec_hit is not None and spec_hit.kind == "qps_surge":
                surge = self._surge(spec_hit)
                self.metrics.offered.inc(len(surge))
                retry.extend(surge)
            now = self.clock()
            while waiting and waiting[0].treq.arrive_s <= now:
                retry.append(waiting.pop(0))
            still = []
            for fl in retry:
                if not self._try_admit(fl):
                    still.append(fl)
            retry = still
            events = self.router.step()
            self._collect_finished()
            self.metrics.inflight.set(len(self._flights))
            if self.on_tick is not None:
                self.on_tick(self)
            self.clock.advance(self.quantum_s)
            self.ticks += 1
            moved = bool(events) or not self._flights
            idle = 0 if moved else idle + 1
            if idle > self.stall_ticks:
                raise RuntimeError(
                    f"traffic run stalled: {self.stall_ticks} event-free "
                    f"quanta with {len(self._flights)} requests in "
                    f"flight")
        return self._finalize(trace)

    def _collect_finished(self):
        """Close out flights whose fin streamed: the router's finished
        table is authoritative for token counts and finish reason
        (covers deadline finishes and adopted histories)."""
        with self._lock:
            done = [fl for fl in self._flights.values() if fl.finished]
            for fl in done:
                self._flights.pop(fl.rid, None)
        for fl in done:
            res = self.router.finished_results.pop(fl.rid, None)
            if res is not None:
                fl.tokens = len(res.output_token_ids)
                fl.reason = res.finish_reason
            self._account(fl)

    def _account(self, fl):
        t = fl.treq
        ttft = (fl.first_t - t.arrive_s) if fl.first_t is not None \
            else float("inf")
        self.metrics.class_ttft(t.cls).observe(
            min(ttft, 1e6))    # inf-safe: expired-before-first-token
        complete = (fl.reason in ("length", "stop", "eos")
                    and fl.tokens >= t.max_new_tokens)
        if fl.reason == "deadline":
            self.metrics.expired.inc()
        if complete and ttft <= t.ttft_slo_s:
            self.metrics.goodput.inc()
        else:
            self.metrics.slo_violation.inc()
        self._done_log.append((fl, ttft, complete))

    def _finalize(self, trace):
        by_class = {}
        goodput = violations = expired = completed = 0
        tokens_expected = tokens_generated = token_loss = 0
        for fl, ttft, complete in self._done_log:
            t = fl.treq
            by_class.setdefault(t.cls, []).append(ttft)
            if complete:
                completed += 1
            if fl.reason == "deadline":
                expired += 1
            else:
                tokens_expected += t.max_new_tokens
                tokens_generated += fl.tokens
                if fl.tokens != t.max_new_tokens:
                    token_loss += t.max_new_tokens - fl.tokens
            if complete and ttft <= t.ttft_slo_s:
                goodput += 1
            else:
                violations += 1
        offered = sum(len(v) for v in by_class.values())
        duration = self.ticks * self.quantum_s
        all_ttft = sorted(x for v in by_class.values() for x in v
                          if x != float("inf"))

        def _pct(vals, q):
            if not vals:
                return None
            i = min(len(vals) - 1, max(0, round(q * (len(vals) - 1))))
            return round(vals[i] * 1e3, 3)

        return {
            "name": self.spec.name,
            "seed": self.spec.seed,
            "offered": offered,
            "completed": completed,
            "goodput": goodput,
            "violations": violations,
            "expired": expired,
            "goodput_frac": round(goodput / offered, 4) if offered
            else 1.0,
            "tokens_expected": tokens_expected,
            "tokens_generated": tokens_generated,
            "token_loss": token_loss,
            "duration_s": round(duration, 6),
            "offered_qps": round(offered / duration, 3) if duration
            else 0.0,
            "ttft_p50_ms": _pct(all_ttft, 0.50),
            "ttft_p99_ms": _pct(all_ttft, 0.99),
            "ttft_by_class_ms": {
                cls: _pct(sorted(x for x in v if x != float("inf")),
                          0.99)
                for cls, v in sorted(by_class.items())},
            "itl_ms": self.metrics.itl.summary(),
            "surge_injected": self._surge_fired,
            "ticks": self.ticks,
        }

    def release(self):
        """Drop the run's registry instruments (refcounted)."""
        self.metrics.release()
