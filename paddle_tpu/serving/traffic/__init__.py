"""paddle_tpu.serving.traffic — deterministic workload models, SLO
autoscaling, and capacity reports for the serving fleet.

The harness every serving claim is measured by (ROADMAP item 4): a
seeded, JSON-able :class:`TrafficSpec` (arrival process, length
mixtures, shared-prefix ratio, deadline classes) compiles into a
byte-identical request trace; the :class:`TrafficDriver` replays it
open-loop against a stock :class:`~paddle_tpu.serving.router.Router`
on a :class:`VirtualClock` (same seed ⇒ identical goodput/SLO
counters); the :class:`SLOAutoscaler` parks/unparks replicas through
the router's own respawn queue with hysteresis; and
:func:`probe_capacity` binary-searches max sustained QPS at a declared
TTFT SLO per replica count into a :class:`CapacityReport`.

Quickstart::

    from paddle_tpu.serving import traffic

    spec = traffic.TrafficSpec(
        seed=0, arrival={"kind": "poisson", "rate_qps": 12.0},
        duration_s=2.0, prompt_len=[[1.0, 4, 16]],
        output_tokens=[[1.0, 4, 8]],
        classes=[traffic.DeadlineClass("interactive", ttft_slo_s=0.5)])
    clock = traffic.VirtualClock()
    router = Router(model, engine_config, num_replicas=2, clock=clock)
    report = traffic.TrafficDriver(router, spec, clock).run()

Chaos composes: put a FaultPlan dict on ``spec.fault_plan`` (e.g. a
``rank_kill`` or a ``serving.traffic.tick`` ``qps_surge``) and the same
run measures goodput under faults.  See docs/serving.md "Traffic, SLOs
& capacity planning".
"""
from paddle_tpu.serving.traffic.autoscaler import (SLO, AutoscalerConfig,
                                                   SLOAutoscaler)
from paddle_tpu.serving.traffic.capacity import (CapacityReport,
                                                 probe_capacity,
                                                 run_traffic)
from paddle_tpu.serving.traffic.driver import (TrafficDriver,
                                               TrafficMetrics,
                                               VirtualClock)
from paddle_tpu.serving.traffic.workload import (DeadlineClass,
                                                 TraceRequest,
                                                 TrafficSpec,
                                                 compile_trace,
                                                 trace_digest)

__all__ = [
    "AutoscalerConfig",
    "CapacityReport",
    "DeadlineClass",
    "SLO",
    "SLOAutoscaler",
    "TraceRequest",
    "TrafficDriver",
    "TrafficMetrics",
    "TrafficSpec",
    "VirtualClock",
    "compile_trace",
    "probe_capacity",
    "run_traffic",
    "trace_digest",
]
