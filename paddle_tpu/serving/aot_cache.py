"""AOT program cache — compiled engine programs as deployment
artifacts.

The Julia-to-TPU model (PAPERS.md, arXiv:1810.09868) treats the
whole-program XLA compilation as THE deployment artifact; this module
applies it to the serving engine's closed program set.  `LLMEngine`
compiles a small, countable family of executables (one prefill per
bucket + one decode + two sampler widths); every one of them is pure
data once compiled, so a cache directory keyed by an engine fingerprint
turns replica scale-out from "recompile the bucket ladder" into "mmap a
few files":

- **Fingerprint.**  :func:`engine_fingerprint` hashes everything a
  compiled program's correctness depends on — model config, engine
  geometry (slots/pages/buckets/dtype), parameter tree (names, shapes,
  dtypes — never values), mesh spec, jax/jaxlib versions, backend
  platform, device kind and count.  Any component changing produces a
  DIFFERENT fingerprint directory, so invalidation is structural: stale
  entries are never loaded, only orphaned (and reapable via
  :meth:`AOTProgramCache.evict_stale`).
- **Entries.**  One file per program
  (``<cache_dir>/<fingerprint>/<program>.jaxprog``), written atomically
  (tmp + rename, the resilience checkpoint discipline) and containing a
  versioned pickle of ``jax.experimental.serialize_executable``'s
  ``(payload, in_tree, out_tree)`` triple.
- **Degradation.**  A backend whose executables refuse serialization, a
  torn/corrupt entry, or a deserialize failure all degrade to a normal
  compile (recorded as a ``serving.aot_cache_miss`` span) — the cache
  can make a boot faster, never wronger.

The observability contract: a cache HIT loads an executable without
touching the recompile log at all — a warm replica boot registers ZERO
compile events — while misses flow through the engine's usual
``note_aot_compile`` choke point.  ``tests/test_serving_router.py``
asserts both directions.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import tempfile

import jax

from paddle_tpu.observability import span

__all__ = ["AOTProgramCache", "engine_fingerprint"]

# bump when the on-disk entry layout changes; folded into every
# fingerprint so old trees are orphaned wholesale, never half-read
FORMAT_VERSION = 1


def _mesh_desc(mesh):
    if mesh is None:
        return None
    return tuple((str(a), int(s)) for a, s in mesh.shape.items())


def engine_fingerprint(model_config, engine_config, params, mesh=None):
    """Hex digest naming the compiled-program family of one engine.

    `params` contributes structure only (sorted name/shape/dtype) —
    weights can be hot-swapped under a fingerprint because XLA compiled
    against their avals, not their values.
    """
    import jaxlib

    devices = jax.devices()
    ec = engine_config
    material = {
        "format": FORMAT_VERSION,
        "model_config": sorted(
            (k, repr(v)) for k, v in vars(model_config).items()
            if not k.startswith("_")),
        "params": [(k, tuple(int(d) for d in v.shape), str(v.dtype))
                   for k, v in sorted(params.items())],
        "engine": (ec.max_num_seqs, ec.page_size, ec.max_model_len,
                   ec.num_pages, tuple(ec.prefill_buckets),
                   str(ec.dtype.__name__ if hasattr(ec.dtype, "__name__")
                       else ec.dtype),
                   # an int8-pool program must never load for an f32
                   # engine (or vice versa) — the pool pytree differs
                   getattr(ec, "kv_cache_dtype", None),
                   # a guarded decode program has an extra operand and
                   # an extra output — structurally different family
                   bool(getattr(ec, "guard", False))),
        "mesh": _mesh_desc(mesh),
        "jax": jax.__version__,
        "jaxlib": getattr(jaxlib, "__version__", "?"),
        "backend": jax.default_backend(),
        "device_kind": getattr(devices[0], "device_kind", ""),
        "n_devices": len(devices),
    }
    return hashlib.sha256(repr(material).encode()).hexdigest()[:24]


class AOTProgramCache:
    """Persisted AOT engine programs under one cache directory.

    Safe to share between replicas (and between processes on one host):
    stores are atomic renames, loads never read a half-written entry,
    and a concurrent double-store of the same key is benign (last
    rename wins, both files identical by construction).
    """

    def __init__(self, cache_dir):
        self.cache_dir = str(cache_dir)
        os.makedirs(self.cache_dir, exist_ok=True)
        # telemetry counters (reporting only; exact counts come from the
        # engine's registry instruments)
        self.hit_count = 0
        self.miss_count = 0
        self.store_count = 0
        self.error_count = 0
        # flipped off after the first "backend refuses serialization" so
        # a TPU runtime without executable serialization pays the failed
        # attempt exactly once
        self._serialize_supported = True

    # ------------------------------------------------------------ paths
    def _entry_path(self, fingerprint, program):
        safe = "".join(c if (c.isalnum() or c in "._-") else "_"
                       for c in str(program))
        return os.path.join(self.cache_dir, fingerprint,
                            f"{safe}.jaxprog")

    def entries(self, fingerprint):
        """Program names currently persisted under `fingerprint`."""
        d = os.path.join(self.cache_dir, fingerprint)
        try:
            return sorted(f[:-len(".jaxprog")] for f in os.listdir(d)
                          if f.endswith(".jaxprog"))
        except OSError:
            return []

    # ------------------------------------------------------------- load
    def load(self, fingerprint, program):
        """Deserialize one program; returns a callable
        ``jax.stages.Compiled`` or None (miss / corrupt / unsupported).
        A corrupt entry is unlinked so the follow-up compile's store
        replaces it."""
        path = self._entry_path(fingerprint, program)
        try:
            with open(path, "rb") as fh:
                version, payload, in_tree, out_tree = pickle.load(fh)
            if version != FORMAT_VERSION:
                raise ValueError(f"format {version} != {FORMAT_VERSION}")
            from jax.experimental import serialize_executable as se
            compiled = se.deserialize_and_load(payload, in_tree, out_tree)
        except FileNotFoundError:
            self.miss_count += 1
            return None
        except Exception as e:  # corrupt / incompatible entry
            self.error_count += 1
            with span("serving.aot_cache_miss", program=str(program),
                      why=type(e).__name__):
                pass
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        self.hit_count += 1
        return compiled

    # ------------------------------------------------------------ store
    def store(self, fingerprint, program, compiled):
        """Serialize `compiled` under (fingerprint, program); returns
        True on success.  Never raises — an unserializable backend or a
        full disk degrades to "no cache", not a serving failure."""
        if not self._serialize_supported:
            return False
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(compiled)
        except Exception as e:
            # ValueError("Compilation does not support serialization")
            # on backends without executable serialization
            self._serialize_supported = False
            self.error_count += 1
            with span("serving.aot_cache_disabled", why=type(e).__name__):
                pass
            return False
        entry = self._entry_path(fingerprint, program)
        d = os.path.dirname(entry)
        try:
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as fh:
                    pickle.dump(
                        (FORMAT_VERSION, payload, in_tree, out_tree), fh)
                os.replace(tmp, entry)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception:
            self.error_count += 1
            return False
        self.store_count += 1
        return True

    # ------------------------------------------------------- maintenance
    def evict_stale(self, keep_fingerprint):
        """Remove every fingerprint directory EXCEPT `keep_fingerprint`
        (deploy hygiene after a model/config/backend change).  Returns
        the evicted fingerprints."""
        evicted = []
        try:
            names = os.listdir(self.cache_dir)
        except OSError:
            return evicted
        for name in names:
            d = os.path.join(self.cache_dir, name)
            if name == keep_fingerprint or not os.path.isdir(d):
                continue
            import shutil
            shutil.rmtree(d, ignore_errors=True)
            evicted.append(name)
        return evicted

    def stats(self):
        return {
            "dir": self.cache_dir,
            "hits": self.hit_count,
            "misses": self.miss_count,
            "stores": self.store_count,
            "errors": self.error_count,
            "serialize_supported": self._serialize_supported,
        }
