"""Runtime lock-order sanitizer — the dynamic half of racelint RL102.

The static pass over-approximates within a module; what it cannot see
is the ACTUAL cross-module acquisition order a live run produces (a
span recorded inside a checkpoint commit takes the recorder lock while
the checkpoint lock is held — an edge no single module shows).  The
tracer closes that gap:

- :class:`LockOrderTracer` monkey-patches ``threading.Lock`` /
  ``threading.RLock`` for its ``with`` scope.  Only locks allocated
  from code inside the traced root (default: the paddle_tpu package)
  are wrapped — stdlib internals (queue, condition-backing locks
  created by threading.py itself) keep the native primitive, so
  nothing outside the repo changes behavior.
- Each wrapped lock is identified by its ALLOCATION SITE (file:line) —
  the same `self._lock = threading.Lock()` line the static model keys
  its lock ids on, which is what makes the static/dynamic cross-check
  possible.
- Every acquisition while other traced locks are held records a
  directed edge (held-site -> acquired-site) per thread.  RLock
  re-entry does not re-edge.

After (or during) a run:

- :meth:`violations` — lock pairs observed in BOTH orders: a real
  inversion the next unlucky schedule turns into a deadlock.
- :meth:`check_static` — dynamic edges that OPPOSE a static RL102
  edge (static says A before B, the run did B before A), plus
  combined-graph cycles: the run proved an order the static model's
  acyclicity argument relied on excluding.

The chaos suite runs with a tracer active (tests/conftest.py arms it
for every ``chaos``-marked test) and asserts zero violations — the
fault-injection suite doubles as a concurrency stress run.

Coverage boundary: only locks ALLOCATED while some tracer has the
factories patched are proxied.  Module-import-time singletons
(``SpanRecorder._lock``, ``MetricsRegistry._lock``, locks inside
``threading.Condition``/``Event``/``queue.Queue``) stay native and
invisible to the dynamic graph — their ordering discipline is covered
by the static RL102 model and the "observability is innermost" rule
in docs/internals.md, not by this tracer.  Per-run objects (engines,
checkpointers, injectors, per-instrument metrics created during the
run) are the traced population.
"""
from __future__ import annotations

import os
import sys
import threading

__all__ = ["LockOrderTracer", "active_tracer"]

_active = None


def active_tracer():
    return _active


class _TracedLock:
    """Proxy over a real Lock/RLock: forwards everything, reports
    acquisition/release to whichever tracer is CURRENTLY active (not
    the one live at allocation) — proxies outlive a tracer's `with`
    scope, and a lock allocated during one traced run must still feed
    the next run's graph instead of a deactivated tracer's.

    Reentrancy (RLock) is handled by per-thread depth counting — only
    the 0->1 acquisition edges into the order graph."""

    __slots__ = ("_lock", "site", "_depth")

    def __init__(self, lock, site):
        self._lock = lock
        self.site = site
        self._depth = {}            # thread id -> reentry depth

    def acquire(self, blocking=True, timeout=-1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            tid = threading.get_ident()
            d = self._depth.get(tid, 0)
            self._depth[tid] = d + 1
            if d == 0:
                tracer = _active
                if tracer is not None:
                    tracer._note_acquire(self, tid)
        return got

    def release(self):
        self._lock.release()
        tid = threading.get_ident()
        if tid not in self._depth:
            # cross-thread handoff (legal for a plain Lock): the
            # acquiring thread's bookkeeping must be undone, not the
            # releasing thread's — otherwise the owner's held stack
            # keeps a phantom entry that fabricates edges forever
            self._depth.clear()
            tracer = _active
            if tracer is not None:
                tracer._note_release(self, tid=None)
            return
        d = self._depth[tid] - 1
        if d <= 0:
            self._depth.pop(tid, None)
            tracer = _active
            if tracer is not None:
                tracer._note_release(self, tid)
        else:
            self._depth[tid] = d

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition(lock=...) support
    def _is_owned(self):
        owned = getattr(self._lock, "_is_owned", None)
        if owned is not None:
            return owned()
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def __repr__(self):
        return f"<TracedLock {self.site[0]}:{self.site[1]}>"


class LockOrderTracer:
    """Context manager recording the actual lock-acquisition graph.

    `roots`: absolute directory prefixes; only locks ALLOCATED from a
    file under one of them are traced (default: the paddle_tpu package
    directory).  `base`: repo root used to relativize sites so dynamic
    ids match the static model's repo-relative paths.
    """

    def __init__(self, roots=None, base=None):
        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # separator-terminated prefixes: /x/pkg must not match a
        # sibling /x/pkg_ext tree
        self.roots = tuple(
            os.path.abspath(r).rstrip(os.sep) + os.sep
            for r in (roots or (here,)))
        self.base = os.path.abspath(base or os.path.dirname(here))
        self._meta = threading.Lock()   # guards edges/locks/stack tables
        self._held = {}                 # thread id -> [locks], by _meta
        self.edges = {}                 # (site_a, site_b) -> count
        self.sites = {}                 # site -> kind
        self._orig = None

    # ---- patching ----
    def __enter__(self):
        global _active
        if _active is not None:
            raise RuntimeError("a LockOrderTracer is already active "
                               "(nesting tracers is not supported)")
        self._orig = (threading.Lock, threading.RLock)
        orig_lock, orig_rlock = self._orig

        def traced_factory(orig, kind):
            tracer = self

            def factory():
                site = tracer._alloc_site()
                lock = orig()
                if site is None:
                    return lock
                with tracer._meta:
                    tracer.sites[site] = kind
                return _TracedLock(lock, site)
            return factory

        threading.Lock = traced_factory(orig_lock, "Lock")
        threading.RLock = traced_factory(orig_rlock, "RLock")
        _active = self
        return self

    def __exit__(self, *exc):
        global _active
        threading.Lock, threading.RLock = self._orig
        _active = None
        return False

    def _alloc_site(self):
        """(repo-relative path, line) of the allocation, when it is
        inside a traced root; else None (lock stays native)."""
        f = sys._getframe(2)
        fname = f.f_code.co_filename
        if not fname.startswith(self.roots):
            return None
        rel = os.path.relpath(fname, self.base).replace(os.sep, "/")
        return (rel, f.f_lineno)

    # ---- acquisition bookkeeping ----
    def _note_acquire(self, lock, tid):
        with self._meta:
            st = self._held.setdefault(tid, [])
            for held in st:
                if held.site != lock.site:
                    key = (held.site, lock.site)
                    self.edges[key] = self.edges.get(key, 0) + 1
            st.append(lock)

    def _note_release(self, lock, tid):
        """Drop `lock` from the holder's stack.  `tid=None` means a
        cross-thread handoff release: whichever thread holds it loses
        it.  Releases can also be out of LIFO order (hand-over-hand),
        so removal is by identity, not by popping."""
        with self._meta:
            stacks = [self._held.get(tid, [])] if tid is not None \
                else list(self._held.values())
            for st in stacks:
                for i in range(len(st) - 1, -1, -1):
                    if st[i] is lock:
                        del st[i]
                        return

    # ---- verdicts ----
    def _violations_locked(self):
        # caller holds self._meta (non-reentrant: snapshot() must not
        # call the public wrapper while holding it)
        return sorted((a, b) for a, b in self.edges
                      if (b, a) in self.edges and a < b)

    def violations(self):
        """Lock-site pairs observed in BOTH orders during the run —
        sorted [(site_a, site_b)] with site_a < site_b."""
        with self._meta:
            return self._violations_locked()

    def check_static(self, static_edges, lock_sites):
        """Cross-check the run against the static RL102 model.

        - `static_edges`: {(held_id, acquired_id): sites} from
          :func:`race_rules.static_lock_order`.
        - `lock_sites`: {lock_id: (path, line)} mapping static ids to
          allocation sites.

        Returns {"conflicts": [...], "combined_cycles": [...]} —
        `conflicts` are dynamic edges whose REVERSE the static model
        requires; `combined_cycles` are cycles that appear only when
        the observed edges are merged into the static graph.  Both
        empty == the run agrees with the model.
        """
        from paddle_tpu.analysis.lock_model import find_cycles
        site_to_id = {site: lid for lid, site in lock_sites.items()}
        static_by_site = set()
        for (a, b) in static_edges:
            sa, sb = lock_sites.get(a), lock_sites.get(b)
            if sa is not None and sb is not None:
                static_by_site.add((sa, sb))
        with self._meta:
            dynamic = set(self.edges)
        conflicts = sorted(
            (a, b) for (a, b) in dynamic
            if (b, a) in static_by_site and (a, b) not in static_by_site)
        static_cycles = set(find_cycles(static_by_site))
        combined_cycles = [
            c for c in find_cycles(static_by_site | dynamic)
            if c not in static_cycles]

        def _name(site):
            return site_to_id.get(site, f"{site[0]}:{site[1]}")

        return {
            "conflicts": [(_name(a), _name(b)) for a, b in conflicts],
            "combined_cycles": [tuple(_name(s) for s in c)
                                for c in combined_cycles],
        }

    def snapshot(self):
        """Plain-dict view (counts only) for reports/tests."""
        with self._meta:
            return {
                "locks_traced": len(self.sites),
                "edges": {f"{a[0]}:{a[1]} -> {b[0]}:{b[1]}": n
                          for (a, b), n in sorted(self.edges.items())},
                "violations": [f"{a[0]}:{a[1]} <-> {b[0]}:{b[1]}"
                               for a, b in self._violations_locked()],
            }
