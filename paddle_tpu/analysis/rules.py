"""tracelint rule registry — the single source of truth for diagnostics.

Every hazard the `to_static` pipeline can hit has a code here (TL0xx
conversion-subset, TL1xx host-sync/purity, TL3xx recompile hazards,
TL4xx post-trace jaxpr findings).  The CLI (`tools/tracelint.py`), the
opt-in `to_static(check=True)` hook, and the *runtime* diagnostics in
`jit/dy2static.py` all pull their message text from this table, so a
user sees the same wording whether the problem is caught ahead of trace
or at trace time.

This module is pure stdlib (no jax import) so the AST pass stays cheap
and importable anywhere — including from `jit/dy2static.py` without an
import cycle.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Rule:
    code: str
    name: str
    message: str       # one-line diagnostic (str.format over kwargs)
    rationale: str     # why this is a hazard under the whole-program trace
    fixit: str         # what the user should do instead


class TraceHazardError(RuntimeError):
    """Named runtime diagnostic for a construct outside the `to_static`
    conversion subset hit with a tensor-valued condition.

    Raised by `jit/dy2static.py` guards instead of letting the generic
    jax concretization error surface; carries the rule code so the CLI
    and the runtime agree on wording.
    """

    def __init__(self, code, filename, lineno, detail=""):
        self.code = code
        self.filename = filename
        self.lineno = lineno
        rule = RULES[code]
        msg = (f"{code} at {filename}:{lineno}: "
               f"{rule.message.format(detail=detail)}\n"
               f"  why: {rule.rationale}\n"
               f"  fix: {rule.fixit}\n"
               f"  (run `python tools/tracelint.py <your file>` to find "
               f"these before tracing)")
        super().__init__(msg)


_R = Rule

RULES = {r.code: r for r in [
    # ---- TL0xx: constructs outside the dy2static conversion subset ----
    _R("TL001", "return-in-converted-loop",
       "`return` inside a loop{detail} — the loop stays plain Python and "
       "a tensor-valued condition there fails at trace time",
       "a lax.while_loop carry cannot hold a value first bound mid-loop, "
       "so dy2static leaves loops containing `return` unconverted; under "
       "a trace the loop condition then hits bool() on a tracer",
       "hoist the result into a variable, `break` out (range-for/while), "
       "and `return` after the loop — or keep the condition "
       "Python-valued"),
    _R("TL002", "break-in-nonrange-for",
       "`break`/`continue` in a non-range `for` loop{detail} — outside "
       "the conversion subset, the loop stays plain Python",
       "only `for <name> in range(...)` lowers to the counter-while form "
       "that can carry the break/continue guard flags",
       "iterate `for i in range(len(xs))` and index, or restructure "
       "without break/continue"),
    _R("TL003", "loop-else-clause",
       "loop `else:` clause{detail} — outside the conversion subset, the "
       "loop stays plain Python",
       "the converted while/for forms have no place for the else block "
       "(it would need a 'did not break' flag across the carry)",
       "move the else body after the loop, guarded on the exit flag you "
       "manage yourself"),
    _R("TL004", "generator-under-trace",
       "`yield` in a function reachable from a `@to_static` entry",
       "generators cannot be traced into one XLA program; convert_call "
       "skips them, so tensor control flow inside stays eager",
       "materialize the sequence into a list before the traced region"),

    # ---- TL1xx: host syncs & trace-time side effects ----
    _R("TL101", "host-sync-numpy",
       "`.{detail}()` on a tensor inside traced code — host sync / "
       "concretization error under the trace",
       "the whole-program trace has no concrete values; .numpy()/.item()/"
       ".tolist() force a device->host transfer that cannot happen inside "
       "one XLA program",
       "keep the value as a tensor; move host-side reads (logging, "
       "thresholds) outside the @to_static function"),
    _R("TL102", "tensor-concretize",
       "`{detail}()` of a tensor value — concretizes under the trace",
       "float()/int()/bool() need a concrete scalar; under the trace they "
       "raise a ConcretizationTypeError (or silently bake a trace-time "
       "constant via __index__)",
       "use tensor arithmetic (the converter handles tensor `if`/`while` "
       "conditions), or compute the scalar before entering traced code"),
    _R("TL103", "tensor-to-numpy-array",
       "np.{detail}() over a tensor value — host transfer under the trace",
       "numpy constructors force concretization; inside the trace this "
       "either errors or silently freezes the value at trace time",
       "use paddle_tpu / jnp ops end to end inside the traced function"),
    _R("TL104", "print-of-tensor",
       "`print` of a tensor value inside traced code — prints a tracer "
       "once at trace time, not per step",
       "side effects run only while tracing; the compiled program never "
       "prints, and what does print is `Traced<...>`, not the value",
       "return the value and print it outside, or drop the print"),
    _R("TL105", "untraced-randomness",
       "`{detail}` inside traced code — evaluated once at trace time and "
       "baked into the program as a constant",
       "host randomness / clocks are not traced: every compiled step "
       "replays the same trace-time value, which is almost never intended",
       "use paddle_tpu's traced RNG ops (paddle.rand/randn, nn dropout) "
       "or pass the value in as an argument"),
    _R("TL106", "trace-time-mutation",
       "mutation of {detail} inside traced code — happens once at trace "
       "time, not per step",
       "appending tensors to module-level / closure lists (or writing "
       "globals) under the trace stores tracers and runs only during "
       "tracing; the compiled step never re-executes it",
       "return values out of the traced function and accumulate outside"),

    # ---- TL3xx: recompile-storm hazards ----
    _R("TL301", "unhashable-static-arg",
       "mutable default argument {detail} on a `@to_static` entry — "
       "unhashable static leaf, falls back to repr() caching",
       "non-tensor args key the compile cache; a list/dict/set default is "
       "repr()-keyed, so equal-but-not-identical values silently miss the "
       "cache and recompile",
       "use a tuple / frozen value, or make the argument a tensor"),
    _R("TL302", "to-static-in-loop",
       "`to_static(...)` constructed inside a loop — every iteration "
       "builds a fresh compile cache",
       "each StaticFunction owns its cache; wrapping per iteration means "
       "nothing is ever reused and every step pays a full XLA compile",
       "hoist the to_static wrapping out of the loop and reuse it"),

    # ---- TL4xx: post-trace jaxpr findings ----
    _R("TL401", "f64-promotion",
       "program contains {detail} values — unintended widening past the "
       "default float32",
       "f64/c128 on TPU runs on the slow path (or is silently demoted); "
       "a stray Python float or np.float64 scalar upcasting an op is the "
       "usual cause",
       "cast inputs explicitly or keep scalars as Python floats under "
       "jax's default x64-disabled config"),
    _R("TL402", "large-baked-constant",
       "constant of {detail} baked into the compiled program",
       "closure-captured arrays are embedded in the executable — they "
       "bloat compile time and HBM, and a changed value silently "
       "recompiles",
       "pass the array as an argument (it becomes a donated/traced "
       "input) instead of closing over it"),
    _R("TL403", "collective-outside-mesh",
       "collective `{detail}` issued with no device mesh initialized",
       "psum/all_gather and friends need a mesh axis to reduce over; "
       "outside `init_mesh`/shard_map they are at best identities and at "
       "worst trace errors on real multi-chip runs",
       "call paddle.distributed.init_mesh(...) (or run under shard_map) "
       "before tracing collectives"),
    _R("TL404", "axis-name-mismatch",
       "collective `{detail}` — axis name not bound by the current mesh",
       "an axis name that doesn't match the mesh's axis_names raises at "
       "dispatch on multi-chip and silently no-ops in single-process "
       "fallbacks",
       "use one of the mesh's declared axis names (see init_mesh "
       "axis_names=...)"),
]}


def message_for(code, detail=""):
    """Formatted one-line message for `code` (shared CLI/runtime text)."""
    return RULES[code].message.format(detail=detail)


# Codes whose AST rules only make sense on functions REACHED from a
# @to_static entry (everything, today — kept explicit for the CLI docs).
AST_CODES = tuple(c for c in RULES if c < "TL400")
JAXPR_CODES = tuple(c for c in RULES if c >= "TL400")
