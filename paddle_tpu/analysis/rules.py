"""tracelint rule registry — the single source of truth for diagnostics.

Every hazard the `to_static` pipeline can hit has a code here (TL0xx
conversion-subset, TL1xx host-sync/purity, TL3xx recompile hazards,
TL4xx post-trace jaxpr findings).  The CLI (`tools/tracelint.py`), the
opt-in `to_static(check=True)` hook, and the *runtime* diagnostics in
`jit/dy2static.py` all pull their message text from this table, so a
user sees the same wording whether the problem is caught ahead of trace
or at trace time.

This module is pure stdlib (no jax import) so the AST pass stays cheap
and importable anywhere — including from `jit/dy2static.py` without an
import cycle.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Rule:
    code: str
    name: str
    message: str       # one-line diagnostic (str.format over kwargs)
    rationale: str     # why this is a hazard under the whole-program trace
    fixit: str         # what the user should do instead


class TraceHazardError(RuntimeError):
    """Named runtime diagnostic for a construct outside the `to_static`
    conversion subset hit with a tensor-valued condition.

    Raised by `jit/dy2static.py` guards instead of letting the generic
    jax concretization error surface; carries the rule code so the CLI
    and the runtime agree on wording.
    """

    def __init__(self, code, filename, lineno, detail=""):
        self.code = code
        self.filename = filename
        self.lineno = lineno
        rule = RULES[code]
        msg = (f"{code} at {filename}:{lineno}: "
               f"{rule.message.format(detail=detail)}\n"
               f"  why: {rule.rationale}\n"
               f"  fix: {rule.fixit}\n"
               f"  (run `python tools/tracelint.py <your file>` to find "
               f"these before tracing)")
        super().__init__(msg)


_R = Rule

RULES = {r.code: r for r in [
    # ---- TL0xx: constructs outside the dy2static conversion subset ----
    _R("TL001", "return-in-converted-loop",
       "`return` inside a loop{detail} — the loop stays plain Python and "
       "a tensor-valued condition there fails at trace time",
       "a lax.while_loop carry cannot hold a value first bound mid-loop, "
       "so dy2static leaves loops containing `return` unconverted; under "
       "a trace the loop condition then hits bool() on a tracer",
       "hoist the result into a variable, `break` out (range-for/while), "
       "and `return` after the loop — or keep the condition "
       "Python-valued"),
    _R("TL002", "break-in-nonrange-for",
       "`break`/`continue` in a non-range `for` loop{detail} — outside "
       "the conversion subset, the loop stays plain Python",
       "only `for <name> in range(...)` lowers to the counter-while form "
       "that can carry the break/continue guard flags",
       "iterate `for i in range(len(xs))` and index, or restructure "
       "without break/continue"),
    _R("TL003", "loop-else-clause",
       "loop `else:` clause{detail} — outside the conversion subset, the "
       "loop stays plain Python",
       "the converted while/for forms have no place for the else block "
       "(it would need a 'did not break' flag across the carry)",
       "move the else body after the loop, guarded on the exit flag you "
       "manage yourself"),
    _R("TL004", "generator-under-trace",
       "`yield` in a function reachable from a `@to_static` entry",
       "generators cannot be traced into one XLA program; convert_call "
       "skips them, so tensor control flow inside stays eager",
       "materialize the sequence into a list before the traced region"),
    _R("TL005", "identity-test-of-branch-bound-name",
       "identity test (`is` / `is not`) of {detail}, which is bound in "
       "only one branch of a convertible `if`",
       "a variable bound in only one branch of a tensor-converted `if` "
       "merges to dy2static's poison sentinel; every ordinary read "
       "raises NameError, but Python's `is` operator cannot be "
       "intercepted — `maybe_bound is None` would silently evaluate "
       "False and take the wrong path",
       "bind the variable on every path (e.g. initialize it to None "
       "before the `if`) when its identity is tested afterwards; if "
       "the test is provably unreachable when unbound (a short-circuit "
       "guard), waive with `# tracelint: disable=TL005` on its line"),

    # ---- TL1xx: host syncs & trace-time side effects ----
    _R("TL101", "host-sync-numpy",
       "`.{detail}()` on a tensor inside traced code — host sync / "
       "concretization error under the trace",
       "the whole-program trace has no concrete values; .numpy()/.item()/"
       ".tolist() force a device->host transfer that cannot happen inside "
       "one XLA program",
       "keep the value as a tensor; move host-side reads (logging, "
       "thresholds) outside the @to_static function"),
    _R("TL102", "tensor-concretize",
       "`{detail}()` of a tensor value — concretizes under the trace",
       "float()/int()/bool() need a concrete scalar; under the trace they "
       "raise a ConcretizationTypeError (or silently bake a trace-time "
       "constant via __index__)",
       "use tensor arithmetic (the converter handles tensor `if`/`while` "
       "conditions), or compute the scalar before entering traced code"),
    _R("TL103", "tensor-to-numpy-array",
       "np.{detail}() over a tensor value — host transfer under the trace",
       "numpy constructors force concretization; inside the trace this "
       "either errors or silently freezes the value at trace time",
       "use paddle_tpu / jnp ops end to end inside the traced function"),
    _R("TL104", "print-of-tensor",
       "`print` of a tensor value inside traced code — prints a tracer "
       "once at trace time, not per step",
       "side effects run only while tracing; the compiled program never "
       "prints, and what does print is `Traced<...>`, not the value",
       "return the value and print it outside, or drop the print"),
    _R("TL105", "untraced-randomness",
       "`{detail}` inside traced code — evaluated once at trace time and "
       "baked into the program as a constant",
       "host randomness / clocks are not traced: every compiled step "
       "replays the same trace-time value, which is almost never intended",
       "use paddle_tpu's traced RNG ops (paddle.rand/randn, nn dropout) "
       "or pass the value in as an argument"),
    _R("TL106", "trace-time-mutation",
       "mutation of {detail} inside traced code — happens once at trace "
       "time, not per step",
       "appending tensors to module-level / closure lists (or writing "
       "globals) under the trace stores tracers and runs only during "
       "tracing; the compiled step never re-executes it",
       "return values out of the traced function and accumulate outside"),

    # ---- TL3xx: recompile-storm hazards ----
    _R("TL301", "unhashable-static-arg",
       "mutable default argument {detail} on a `@to_static` entry — "
       "unhashable static leaf, falls back to repr() caching",
       "non-tensor args key the compile cache; a list/dict/set default is "
       "repr()-keyed, so equal-but-not-identical values silently miss the "
       "cache and recompile",
       "use a tuple / frozen value, or make the argument a tensor"),
    _R("TL302", "to-static-in-loop",
       "`to_static(...)` constructed inside a loop — every iteration "
       "builds a fresh compile cache",
       "each StaticFunction owns its cache; wrapping per iteration means "
       "nothing is ever reused and every step pays a full XLA compile",
       "hoist the to_static wrapping out of the loop and reuse it"),

    # ---- TL4xx: post-trace jaxpr findings ----
    _R("TL401", "f64-promotion",
       "program contains {detail} values — unintended widening past the "
       "default float32",
       "f64/c128 on TPU runs on the slow path (or is silently demoted); "
       "a stray Python float or np.float64 scalar upcasting an op is the "
       "usual cause",
       "cast inputs explicitly or keep scalars as Python floats under "
       "jax's default x64-disabled config"),
    _R("TL402", "large-baked-constant",
       "constant of {detail} baked into the compiled program",
       "closure-captured arrays are embedded in the executable — they "
       "bloat compile time and HBM, and a changed value silently "
       "recompiles",
       "pass the array as an argument (it becomes a donated/traced "
       "input) instead of closing over it"),
    _R("TL403", "collective-outside-mesh",
       "collective `{detail}` issued with no device mesh initialized",
       "psum/all_gather and friends need a mesh axis to reduce over; "
       "outside `init_mesh`/shard_map they are at best identities and at "
       "worst trace errors on real multi-chip runs",
       "call paddle.distributed.init_mesh(...) (or run under shard_map) "
       "before tracing collectives"),
    _R("TL404", "axis-name-mismatch",
       "collective `{detail}` — axis name not bound by the current mesh",
       "an axis name that doesn't match the mesh's axis_names raises at "
       "dispatch on multi-chip and silently no-ops in single-process "
       "fallbacks",
       "use one of the mesh's declared axis names (see init_mesh "
       "axis_names=...)"),

    # ---- SL1xx: sharding (shardlint, analysis/shard_rules.py) ----
    _R("SL101", "large-replicated-array",
       "large program input {detail} is fully replicated on every device "
       "of the mesh",
       "a replicated array costs its full size in HBM on EVERY chip; past "
       "a few MiB that is usually an unannotated weight the mesh was "
       "supposed to shard",
       "annotate it with shard_tensor(t, ...) / a PartitionSpec over a "
       "mesh axis, or accept it into the shardlint baseline if the "
       "replication is intentional"),
    _R("SL102", "unsharded-optimizer-state",
       "optimizer state {detail} is replicated under a data-parallel mesh",
       "optimizer accumulators are pure per-parameter state — replicating "
       "them across dp ranks wastes HBM that ZeRO stage 1/2 reclaims for "
       "free (grads already reduce-scatter)",
       "wrap with distributed.sharding.group_sharded_parallel (stage "
       "'os'/'os_g'), or shard the accumulator like its parameter"),
    _R("SL103", "resharding-thrash",
       "value resharded {detail} — an A->B->A constraint chain",
       "each conflicting sharding constraint materializes a resharding "
       "collective; bouncing a value between two layouts pays the "
       "transfer twice for no net layout change",
       "pick one layout for the value's lifetime, or move the consumer "
       "needing the other layout next to the first constraint"),

    # ---- SL2xx: collective safety ----
    _R("SL201", "collective-order-mismatch",
       "cond branches issue different collective sequences ({detail})",
       "under SPMD a collective is a rendezvous: if shards can disagree "
       "on the branch (or the branches order their collectives "
       "differently) some chips wait forever — a silent multi-chip "
       "deadlock",
       "hoist the collectives out of the cond, or make every branch "
       "issue the SAME collectives in the SAME order"),
    _R("SL202", "all-gather-over-budget",
       "all_gather materializes {detail} — past the per-chip HBM budget",
       "all_gather multiplies the operand by the axis size on EVERY "
       "chip; a gather that exceeds the HBM budget OOMs at runtime even "
       "though each shard individually fits",
       "keep the value sharded (reduce_scatter + local compute), or "
       "gather in chunks"),
    _R("SL203", "loop-invariant-collective",
       "collective `{detail}` inside a scan body has loop-invariant "
       "operands",
       "XLA does not hoist collectives out of loops: a psum/all_gather "
       "of values that never change inside the scan pays the full "
       "network latency every iteration",
       "compute the collective once before the scan and pass the result "
       "in as a carry/const"),

    # ---- SL3xx: memory & layout cost ----
    _R("SL301", "peak-hbm-over-budget",
       "estimated peak HBM {detail}",
       "the liveness estimate over the traced program exceeds the "
       "declared per-chip budget — the step will OOM (or silently spill) "
       "on real silicon",
       "shard or rematerialize the top contributors (see the cost "
       "report), shrink the batch, or raise the documented budget"),
    _R("SL302", "mxu-padding-waste",
       "operand {detail} — padded to the MXU tile, wasting compute/HBM",
       "TPU tiles are (sublane, 128-lane) blocks — 8x128 f32, 16x128 "
       "bf16; a dim just past a tile boundary pays for the whole next "
       "tile in both memory and MXU cycles",
       "round the dim to a multiple of 128 (lane) / the dtype sublane "
       "count, e.g. pad vocab or hidden sizes at model-config time"),
    _R("SL303", "f32-param-bf16-compute",
       "f32 input {detail} is only consumed through a bf16 cast",
       "storing a parameter in f32 when every use first converts it to "
       "bf16 doubles its HBM residency and the cast bandwidth every "
       "step",
       "store the parameter in bf16 (keep an f32 master copy only where "
       "the optimizer needs it)"),
    # ---- NL1xx: precision loss (numlint, num_rules.py/dtype_flow.py) ----
    _R("NL101", "narrow-accumulation",
       "reduction {detail} accumulates in a narrow dtype",
       "summing N values in bf16 keeps an 8-bit mantissa on the RUNNING "
       "total: past a few hundred addends the small contributions are "
       "absorbed entirely (classic bias-grad / loss-mean corruption); "
       "the MXU accumulates dot products wide in hardware, but a "
       "reduce_sum lowers to exactly the narrow serial sum it says",
       "accumulate wide: preferred_element_type=float32 on the "
       "dot_general, or cast the operand up before the reduce and back "
       "down after (one rounding of the result, not one per addend)"),
    _R("NL102", "double-rounding-roundtrip",
       "f32 value narrowed then re-widened ({detail}) while the wide "
       "value was still live",
       "float32(bfloat16(x)) != x — the round trip costs 16 mantissa "
       "bits; when the original wide value still has live consumers the "
       "narrow copy existed only in passing, so downstream math pays "
       "double rounding for zero residency savings",
       "consume the original wide value directly; narrow only at a "
       "residency boundary where the wide copy genuinely dies "
       "(a cast chain rooted at a PROGRAM INPUT is shardlint SL303's "
       "finding, not this one — see docs/shardlint.md)"),
    _R("NL103", "narrow-master-state",
       "optimizer-plane state {detail} is stored narrow without a "
       "moment_dtype opt-in",
       "param update math below ~1e-3 relative step size rounds to ZERO "
       "in bf16 — narrow master weights stop learning late in training, "
       "and narrow moments bias the adaptive scale; PR 10 pinned this "
       "invariant dynamically (SL303=0 on the flagship), numlint proves "
       "it statically on every audited program",
       "store params and moments f32 (master weights); narrow moments "
       "only through the explicit Adam/AdamW moment_dtype opt-in, which "
       "declares the tolerance contract"),

    # ---- NL2xx: stability ----
    _R("NL201", "unstabilized-narrow-transcendental",
       "`{detail}` on a narrow dtype with no stabilization upstream",
       "exp overflows bf16 at x>88 ln2-scaled and float16 at x>11; "
       "log/div amplify near zero — without a max-subtraction (softmax) "
       "or eps-guard (denominators) the narrow evaluation saturates to "
       "inf/nan exactly on the outlier activations that matter",
       "subtract the row max before exp (jax.nn.softmax does), add an "
       "eps before log/div, or upcast the operand to f32 for the "
       "transcendental and narrow the result"),
    _R("NL202", "narrow-scan-carry",
       "scan carry {detail} is narrower than its body math",
       "a carry that the body widens, updates, and re-narrows rounds "
       "the running value EVERY iteration — error compounds linearly "
       "with loop length, unlike a single end-of-loop rounding",
       "keep the carry at the body's compute dtype and narrow once "
       "after the scan (the carry is live-range-bounded; residency "
       "savings are per-iteration only)"),

    # ---- NL3xx: quantization readiness ----
    _R("NL301", "scale-free-quantized-consumption",
       "quantized value {detail} consumed with no adjacent scale "
       "operand",
       "int8/fp8 codes are meaningless without their quantization "
       "scale: math on raw codes silently treats quantization bins as "
       "real units — the KV-quantization plane (ROADMAP item 2) must "
       "carry a per-page scale next to every pool read",
       "dequantize first (convert + multiply by the scale), or pass "
       "the scale into the consuming kernel alongside the codes"),
    _R("NL302", "dequant-requant-roundtrip",
       "dequantized value {detail} immediately requantized",
       "a dequant->requant chain whose intermediate float has no other "
       "consumer materializes a full-width tensor only to round it "
       "away again — and the two roundings need not compose to the "
       "identity even at equal scales",
       "fuse the rescale into one integer/fp8-domain op (or one "
       "convert with the combined scale) instead of bouncing through "
       "floats"),

    # ---- KL1xx: Pallas kernel interiors (kernlint, kernel_rules.py) ----
    _R("KL101", "block-tile-misalignment",
       "block shape {detail} is not a multiple of the dtype's native "
       "TPU tile",
       "VMEM tiles are (sublane, 128-lane) blocks — (8,128) f32, "
       "(16,128) bf16, (32,128) int8/fp8; a BlockSpec dim that is "
       "neither 1, the full array dim, nor a tile multiple forces "
       "Mosaic to pad every block copy, wasting VMEM and MXU cycles "
       "on every grid step (the in-kernel twin of SL302)",
       "round the block dim to the dtype's sublane multiple / 128 "
       "lanes (ops/pallas/norm.py `_sublane` + `_auto_block_rows` are "
       "the house helpers), or pad the array so the full dim is the "
       "block"),
    _R("KL102", "vmem-over-budget",
       "estimated VMEM footprint {detail}",
       "Pallas double-buffers every grid-iterated block, and scratch "
       "lives alongside — the static estimate (tile-padded block "
       "buffers x2 + scratch) exceeding the per-core VMEM budget means "
       "Mosaic either spills or refuses to compile, discovered only "
       "after a full XLA lowering on real silicon",
       "shrink the block shape (fewer rows per grid step), move large "
       "accumulators to f32 scratch only where needed, or iterate an "
       "extra grid dimension instead of widening blocks"),
    _R("KL103", "narrow-in-kernel-accumulation",
       "kernel body {detail} accumulates in a narrow dtype",
       "numlint's NL101 deliberately stops at the pallas_call boundary "
       "(the body is VMEM-resident, not HBM traffic) — but inside the "
       "kernel the same math rules hold: a dot without "
       "preferred_element_type=f32 or a bf16 += reduction carry rounds "
       "the running total every block, and the wrong answer never "
       "surfaces as an error",
       "pass preferred_element_type=jnp.float32 to in-kernel dots, "
       "keep accumulator refs/scratch f32, and cast once when storing "
       "the block result"),
    _R("KL104", "input-output-alias-hazard",
       "input_output_aliases {detail}",
       "an aliased pair shares one buffer: a shape/dtype mismatch "
       "corrupts the donated storage layout, and a read of the aliased "
       "input AFTER the aliased output's block was stored observes the "
       "new value on TPU while interpret mode still shows the old one "
       "— a silent TPU-only wrong answer",
       "alias only identically-shaped/dtyped pairs, and finish every "
       "read of the aliased input ref before the first store to its "
       "aliased output ref"),
    _R("KL105", "grid-coverage-mismatch",
       "grid x block {detail}",
       "Pallas writes exactly the blocks the index maps name: an "
       "output region no grid step covers keeps uninitialized garbage, "
       "an input tail never mapped is silently unprocessed, and two "
       "NON-consecutive grid steps naming the same output block "
       "overwrite each other's result (consecutive revisits are the "
       "legal accumulation pattern)",
       "make ceil(array_dim / block_dim) grid steps per dim with an "
       "identity-ish index map, or mask the overlap; data-dependent "
       "(scalar-prefetch) maps are skipped — keep them total by "
       "construction"),
    _R("KL106", "unguarded-ragged-tail",
       "partial final block {detail} read without a guard",
       "when block x grid overshoots the array, the final block's "
       "out-of-range rows are padding with undefined contents; a "
       "reduction or dot that consumes them unmasked folds garbage "
       "into real outputs — the exact hazard class a ragged "
       "paged-attention kernel lives in",
       "guard tail loads with @pl.when(pid < full_blocks), mask with "
       "broadcasted_iota against the true length, or pad the operand "
       "to a block multiple before the call (the norm.py _pad_rows "
       "pattern)"),

    # ---- RL1xx: host-runtime concurrency (racelint, race_rules.py) ----
    _R("RL101", "unguarded-shared-attribute",
       "{detail} is accessed from multiple thread roots with no "
       "consistent lock",
       "attributes reached from two thread roots with empty (or "
       "disjoint) lock sets are classic data races: lost updates, torn "
       "reads, and ordering bugs that only fire under load — exactly "
       "the class of bug the GIL hides until a preemption lands between "
       "a read and its write-back",
       "guard every access with ONE lock (document it next to the "
       "attribute), make the attribute a thread-safe type "
       "(Queue/Event), or confine it to a single thread"),
    _R("RL102", "lock-order-inversion",
       "lock-order cycle: {detail}",
       "two threads taking the same locks in opposite orders deadlock "
       "the moment their windows overlap; the acquired-while-holding "
       "graph must stay acyclic for the whole package, not per module",
       "pick one global order (docs/internals.md 'Threading model & "
       "lock hierarchy') and re-nest the offending acquisition — or "
       "drop to a single lock"),
    _R("RL103", "blocking-call-under-lock",
       "blocking {detail} while holding a lock",
       "a lock held across join/IO/un-timed queue waits turns every "
       "other acquirer into a convoy behind the slow operation — and "
       "into a deadlock if the blocking operation itself needs the "
       "lock (a callback, a signal handler, a joined thread)",
       "move the blocking call outside the critical section: snapshot "
       "state under the lock, release, then block"),
    _R("RL104", "unsafe-signal-handler",
       "signal handler does more than set a flag: {detail}",
       "Python signal handlers run between bytecodes of WHATEVER the "
       "main thread was doing: acquiring a lock the interrupted code "
       "holds (buffered IO locks included — print!) deadlocks, and "
       "allocation/IO there is reentrancy-unsafe by construction",
       "set a flag (threading.Event) in the handler and do the real "
       "work at a polled step boundary — the drain pattern "
       "resilience.preemption documents"),
    _R("RL105", "thread-lifecycle-leak",
       "{detail}",
       "a non-daemon thread nobody joins blocks interpreter exit; an "
       "executor nobody shuts down leaks its workers; a loop with no "
       "stop path cannot be drained on preemption — all three turn "
       "clean shutdowns into hangs",
       "join (or make daemon) every thread, `shutdown()` every "
       "executor, and give every loop a stop Event the owner sets"),

    # ---- RL2xx: atomicity ----
    _R("RL201", "check-then-act-toctou",
       "check-then-act on {detail} outside its guarding lock",
       "`if key in shared: shared[key]...` is two operations; another "
       "thread can invalidate the check before the act (the serving "
       "metrics `_release_labels` bug this repo already shipped once) — "
       "the attribute has a lock, but this site doesn't hold it",
       "take the attribute's lock around the WHOLE check+act sequence, "
       "or use an atomic primitive (dict.setdefault, dict.pop(k, "
       "None))"),

    # ================= PLxxx: protolint (coordination-KV protocols) ====
    # Cross-process protocol audit over the coordination-KV surfaces
    # (kv_model.py / proto_rules.py; tools/protolint.py; docs/
    # protolint.md).  PL1xx: key lifecycle & liveness; PL2xx: wire
    # payload & ordering discipline.
    _R("PL101", "kv-key-leak",
       "KV key {detail} is set but never reclaimed",
       "a key nobody consumes or reaps accrues in the coordination "
       "store for the life of the service: per-round keys grow O(steps),"
       " and keys outside the launch namespace survive the end-of-run "
       "namespace reap entirely — next launch reads this run's debris "
       "(stale heartbeats flag healthy hosts dead, stale round keys "
       "corrupt fresh rendezvous)",
       "give every set key a consumer AND a reap: delete-on-consume for "
       "exactly-once lanes, a two-rounds-behind prefix sweep for round "
       "keys (collective._coord_reap is the model), and root every key "
       "under coord_namespace() so finalize()'s namespace reap is the "
       "backstop"),
    _R("PL102", "consume-without-delete",
       "exactly-once key {detail} is consumed but never deleted",
       "a seq-numbered lane key left in the store after its one "
       "legitimate read is a double-delivery hazard: a wedged peer that "
       "resumes (SIGSTOP→SIGCONT) or a retried reader re-consumes the "
       "same payload — the exactly-once contract of the wire lane "
       "silently becomes at-least-once",
       "delete the key the moment it is consumed (wire.await_response/"
       "read_request pattern), or cover the whole round with a "
       "non-root prefix reap that runs before the seq can recycle"),
    _R("PL103", "unbounded-kv-wait",
       "unbounded blocking KV get: {detail}",
       "a blocking_key_value_get with no finite deadline wedges the "
       "process forever when the peer died before setting the key — "
       "the exact failure the fleet watchdog exists to convert into a "
       "typed CollectiveTimeout with a DEAD verdict",
       "route every wait through resilience.fleet.kv_get_bytes (sliced "
       "deadline + RetryPolicy backoff + abort_if watchdog hook) or "
       "pass an explicit finite timeout_in_ms"),
    _R("PL104", "cross-role-wait-cycle",
       "cross-role KV wait cycle: {detail}",
       "role A blocking on a key only role B sets while B blocks on "
       "one only A sets is the multi-process analogue of a lock-order "
       "inversion (RL102): with unbounded waits the fleet deadlocks "
       "the first time both sides enter their waits, and no "
       "single-process tracer can see it",
       "break the cycle by ordering the protocol (set your side's key "
       "BEFORE blocking on the peer's — the wire req/rsp lane's "
       "set-then-get shape) or bound one side with a deadline + retry"),
    _R("PL105", "heartbeat-deadline-mismatch",
       "liveness deadline vs heartbeat interval mismatch: {detail}",
       "a staleness deadline that is not comfortably larger than the "
       "publish interval (deadline >= interval x miss-budget) flags "
       "healthy hosts dead on a single delayed beat — one GC pause or "
       "slow KV round trip away from a spurious fleet reconfigure",
       "derive the deadline from the interval with an explicit miss "
       "budget (FleetConfig's suspect_after_s = 3x / dead_after_s = 6x "
       "heartbeat_interval_s is the house pattern) and validate the "
       "ratio at config time"),
    _R("PL201", "untyped-error-envelope",
       "wire response without a typed-error envelope: {detail}",
       "an RPC lane whose responses carry only the success payload has "
       "no way to ship a replica-side exception: the caller times out "
       "on application errors and every failure collapses into "
       "'peer dead', losing the typed backpressure (AdmissionRejected) "
       "the routing layer dispatches on",
       "marshal every response through an ok/err discriminated "
       "envelope (wire.post_response + _marshal_error/_unmarshal_error "
       "is the house pattern) and post the error branch from the "
       "serve loop's except handler"),
    _R("PL202", "seq-reuse",
       "seq counter feeding {detail} can be reused non-monotonically",
       "a sequence slot rewound outside construction lets a fresh "
       "request collide with an undeleted key from the previous life "
       "of the counter — the lane silently pairs a new request with a "
       "stale response (or vice versa), breaking exactly-once pairing",
       "make the counter monotonic for the lifetime of the key "
       "namespace: reset it only together with a namespace/generation "
       "bump (collective.reset_coord_rounds documents that coupling)"),
]}


def message_for(code, detail=""):
    """Formatted one-line message for `code` (shared CLI/runtime text)."""
    return RULES[code].message.format(detail=detail)


# Codes whose AST rules only make sense on functions REACHED from a
# @to_static entry (everything AST-side, today — kept explicit for the
# CLI docs).  SLxxx codes are all post-trace (jaxpr-level): the
# shardlint passes in shard_rules.py / cost_audit.py.  RLxxx codes are
# the host-runtime concurrency family (racelint, race_rules.py).
AST_CODES = tuple(c for c in RULES if c.startswith("TL") and c < "TL400")
JAXPR_CODES = tuple(c for c in RULES
                    if c.startswith("SL") or (c.startswith("TL")
                                              and c >= "TL400"))
SHARDLINT_CODES = tuple(c for c in RULES if c.startswith("SL"))
RACELINT_CODES = tuple(c for c in RULES if c.startswith("RL"))
NUMLINT_CODES = tuple(c for c in RULES if c.startswith("NL"))
KERNLINT_CODES = tuple(c for c in RULES if c.startswith("KL"))
PROTOLINT_CODES = tuple(c for c in RULES if c.startswith("PL"))
