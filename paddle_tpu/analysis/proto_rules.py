"""protolint rules: the PLxxx family over :mod:`kv_model`.

Whole-package pass over the coordination-KV world model — every key
the package constructs, normalized to its construction-site pattern,
with its set/get/delete flow and the process role of each site.  The
seven hand-rolled protocols this audits (fleet wire/disagg/server,
the ``_coord_*`` collectives, elastic heartbeats, sentinel votes,
resilience.fleet) enforce exactly-once and key-lifecycle invariants
by convention only; these rules turn the conventions into a gate.

Findings resolve to real file:line sites and honor the same
``# protolint: disable=PLxxx`` suppression comments the sibling
analyzers use (``# tracelint:`` is the universal spelling; foreign
family spellings like ``# racelint:`` cannot waive PL rules).  The
pass over-approximates on purpose: a finding is a *hazard*, and the
checked-in baseline (tools/protolint_baseline.json) absorbs the
reviewed backlog so ``--check`` fails only on regressions.

Rule summary (catalogue text lives in :mod:`rules`):

- **PL101** key set but never reclaimed — no consumer and no covering
  delete, or the key lives outside the run namespace (so the
  end-of-run root reap can't reach it) with no delete of its own.
- **PL102** exactly-once key (a ``<seq>``-bearing lane) consumed
  without a covering delete — double-delivery hazard.
- **PL103** un-timed/unbounded raw ``blocking_key_value_get`` —
  deadline-bounded and watchdog/abort-covered sites are exempt.
- **PL104** cross-role wait cycle: role A blocks unbounded on a key
  only role B sets while B blocks on one only A sets (the
  multi-process analogue of RL102).
- **PL105** liveness deadline does not clear the heartbeat interval's
  miss budget (deadline must be ≥ interval × 2).
- **PL201** response lane of a request/response pair whose payload
  carries no typed-error envelope — a failing peer can only time the
  caller out instead of delivering the error.
- **PL202** the seq counter feeding an exactly-once key can be reset
  non-monotonically, so key identities may be reused.
"""
from __future__ import annotations

import ast
import os

from paddle_tpu.analysis import kv_model
from paddle_tpu.analysis.kv_model import PackageModel
from paddle_tpu.analysis.rules import message_for
from paddle_tpu.analysis.visitor import (Finding, iter_py_files,
                                         parse_suppressions, rel_path)

# PL105's miss budget: a peer must be allowed to miss this many
# heartbeats before the deadline declares it dead (docs/protolint.md)
_MISS_BUDGET = 2.0


def modname_for(path, base=None):
    rel = rel_path(path, base)
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def build_package_model(paths, base=None):
    """Parse every .py under `paths` into one PackageModel.  Returns
    (model, {path: (suppressions, skip_file, lines)}, [parse-error
    Finding])."""
    pm = PackageModel()
    sups = {}
    errors = []
    for path in iter_py_files(paths):
        # the analyzers themselves are not protocol surfaces: the KV
        # tracer's pass-through proxy methods and residual-key sweep
        # would otherwise register as wildcard consumers/deleters and
        # mask real leaks everywhere else in the package
        norm = path.replace(os.sep, "/")
        if "/analysis/" in norm or norm.startswith("analysis/"):
            continue
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        except OSError:
            continue
        rel = rel_path(path, base)
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            errors.append(Finding(
                path=rel, line=e.lineno or 1, col=e.offset or 0,
                code="PL000", message=f"syntax error: {e.msg}"))
            continue
        sup, skip = parse_suppressions(source)
        sups[rel] = (sup, skip, source.splitlines())
        mm = kv_model.ModuleBuilder(
            path=rel, modname=modname_for(path, base),
            tree=tree).build()
        pm.add(mm)
    pm.finalize()
    return pm, sups, errors


def _finding(op, code, detail):
    return Finding(path=op.path, line=op.line, col=op.col, code=code,
                   message=message_for(code, detail))


# ------------------------------------------------------------ PL101
def _check_key_leak(pm):
    out = []
    for c, info in sorted(pm.pattern_table.items()):
        if not info.sets or c == "<*>":
            continue
        consumed = bool(info.gets) or bool(pm.dir_get_covers(c))
        reclaimed = bool(pm.delete_covers(c))
        site = min(info.sets, key=lambda o: (o.path, o.line))
        if not consumed and not reclaimed:
            out.append(_finding(
                site, "PL101",
                f"'{info.display}' (no consumer and no covering "
                f"delete)"))
        elif not info.ns_rooted and not reclaimed:
            # outside the run namespace the end-of-run root reap
            # (key_value_delete of the namespace) can't reach it
            out.append(_finding(
                site, "PL101",
                f"'{info.display}' (outlives the run namespace; "
                f"nothing ever deletes it)"))
    return out


# ------------------------------------------------------------ PL102
def _check_consume_without_delete(pm):
    out = []
    for c, info in sorted(pm.pattern_table.items()):
        if not info.seq_lane or not info.gets:
            continue
        if pm.delete_covers(c):
            continue
        site = min(info.gets, key=lambda o: (o.path, o.line))
        out.append(_finding(
            site, "PL102",
            f"'{info.display}' (a crashed-and-restarted consumer "
            f"re-reads the stale payload)"))
    return out


# ------------------------------------------------------------ PL103
def _check_unbounded_get(pm):
    out = []
    for f in pm.funcs:
        for item in f.items:
            if item[0] != "op":
                continue
            op = item[1]
            if op.kind != "get_raw" or op.timed or op.watchdog:
                continue
            what = op.pattern if not op.opaque else f.qualname
            out.append(_finding(
                op, "PL103",
                f"'{what}' (no deadline: a dead peer wedges this "
                f"process forever)"))
    return out


# ------------------------------------------------------------ PL104
def _check_cross_role_cycle(pm):
    edges = {}      # (role_a, role_b) -> (op, canon)
    for f in pm.top_funcs():
        role = f.role
        for op in pm.expanded_ops(f):
            if op.kind != "get_raw" or op.timed or op.watchdog \
                    or op.opaque:
                continue
            info = pm.pattern_table.get(op.canon)
            if info is None:
                continue
            for setter in sorted(info.set_roles):
                if setter != role:
                    edges.setdefault((role, setter), (op, op.canon))
    out = []
    for cycle in _cycles({a: set() for a, _ in edges} | {
            b: set() for _, b in edges}, edges):
        ops = [edges[e] for e in cycle]
        site = ops[0][0]
        desc = " -> ".join(f"{a} waits on {b} ('{edges[(a, b)][1]}')"
                           for a, b in cycle)
        out.append(_finding(site, "PL104", desc))
    return out


def _cycles(nodes, edges):
    """Elementary cycles in the (tiny, ≤4-node) role graph, each as
    an edge list; deduped by node set."""
    adj = {n: [] for n in nodes}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    seen = set()
    found = []

    def dfs(start, node, path):
        for nxt in adj.get(node, ()):
            if nxt == start:
                key = frozenset(p[0] for p in path) | {node}
                if key not in seen:
                    seen.add(key)
                    found.append(path + [(node, nxt)])
            elif all(nxt != p[0] for p in path) and nxt != node:
                dfs(start, nxt, path + [(node, nxt)])

    for n in sorted(adj):
        dfs(n, n, [])
    return found


# ------------------------------------------------------------ PL105
def _check_liveness_budget(pm):
    out = []
    for lp in pm.liveness_pairs:
        if lp.deadline >= _MISS_BUDGET * lp.interval:
            continue
        f = Finding(
            path=lp.path, line=lp.line, col=0, code="PL105",
            message=message_for(
                "PL105",
                f"{lp.scope}.{lp.deadline_name}={lp.deadline:g}s "
                f"allows fewer than {_MISS_BUDGET:g} missed beats at "
                f"{lp.interval_name}={lp.interval:g}s"))
        out.append(f)
    return out


# ------------------------------------------------------------ PL201
def _lane_pairs(pm):
    """Request/response canon pairs: same shape, exactly one
    differing segment, both differing segments literal."""
    canons = [c for c, info in pm.pattern_table.items()
              if info.sets or info.gets]
    pairs = []
    for i, a in enumerate(canons):
        sa = a.split("/")
        for b in canons[i + 1:]:
            sb = b.split("/")
            if len(sa) != len(sb):
                continue
            diff = [k for k in range(len(sa)) if sa[k] != sb[k]]
            if len(diff) == 1 and "<" not in sa[diff[0]] \
                    and "<" not in sb[diff[0]]:
                pairs.append((a, b))
    return pairs


def _check_error_envelope(pm):
    # response side of a pair = the lane one function GETS after
    # SETTING the other (the initiator's post-then-await order)
    responses = set()
    pairs = _lane_pairs(pm)
    if pairs:
        for f in pm.top_funcs():
            ops = pm.expanded_ops(f)
            for a, b in pairs:
                for req, rsp in ((a, b), (b, a)):
                    set_at = [i for i, op in enumerate(ops)
                              if op.kind == "set" and op.canon == req]
                    get_at = [i for i, op in enumerate(ops)
                              if op.kind in ("get", "get_raw")
                              and op.canon == rsp]
                    if set_at and get_at and min(set_at) < max(get_at):
                        responses.add(rsp)
    out = []
    for rsp in sorted(responses):
        info = pm.pattern_table[rsp]
        if not info.sets:
            continue        # produced outside the package
        if any(op.envelope for op in info.sets):
            continue
        site = min(info.sets, key=lambda o: (o.path, o.line))
        out.append(_finding(
            site, "PL201",
            f"'{info.display}' (a peer failure can only surface "
            f"as the initiator's timeout)"))
    return out


# ------------------------------------------------------------ PL202
def _check_seq_reuse(pm):
    by_qual = {}
    for f in pm.funcs:
        by_qual[f.qualname] = f
    out = []
    for c, info in sorted(pm.pattern_table.items()):
        seen_site = set()
        for op in info.sets:
            if not op.seq_src or (op.path, op.line) in seen_site:
                continue
            kind = op.seq_src[0]
            detail = None
            if kind == "attr":
                _, cls, attr = op.seq_src
                assigns = pm.attr_assigns.get((cls, attr), ())
                resets = [a for a in assigns
                          if a[2] and a[0] != "__init__"]
                if resets:
                    detail = (f"'{info.display}' ({cls}.{attr} is "
                              f"reset to a constant in "
                              f"{resets[0][0]}())")
            elif kind == "global":
                _, mod, name = op.seq_src
                resets = pm.global_const_assigns.get((mod, name), ())
                if resets:
                    detail = (f"'{info.display}' ({name} is rewound "
                              f"by {resets[0][0]}())")
            elif kind == "local":
                _, qual, name = op.seq_src
                f = by_qual.get(qual)
                assigns = (f.local_assigns.get(name, ())
                           if f is not None else ())
                augs = [a[0] for a in assigns if a[2]]
                consts = [a[0] for a in assigns if a[1]]
                if augs and any(cl > min(augs) for cl in consts):
                    detail = (f"'{info.display}' (local counter "
                              f"{name} is re-seeded after it has "
                              f"advanced)")
            if detail:
                seen_site.add((op.path, op.line))
                out.append(_finding(op, "PL202", detail))
    return out


ALL_CHECKS = (
    _check_key_leak,
    _check_consume_without_delete,
    _check_unbounded_get,
    _check_cross_role_cycle,
    _check_liveness_budget,
    _check_error_envelope,
    _check_seq_reuse,
)


def lint_package(paths, base=None):
    """The protolint entry: AST-model every file under `paths`, run
    the PL rules package-wide, apply suppressions.  Returns
    [Finding]."""
    pm, sups, findings = build_package_model(paths, base=base)
    for check in ALL_CHECKS:
        findings.extend(check(pm))
    out = []
    for f in findings:
        entry = sups.get(f.path)
        if entry is not None:
            sup, skip, lines = entry
            if skip:
                continue
            codes = sup.get(f.line, ())
            if "ALL" in codes or "ALL:PL" in codes or f.code in codes:
                continue
            if 1 <= f.line <= len(lines):
                f.source_line = lines[f.line - 1].strip()
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out


def static_kv_model(paths, base=None):
    """The PackageModel alone — what :mod:`kv_tracer`'s
    ``check_static`` cross-checks runtime event streams against."""
    pm, _sups, _errors = build_package_model(paths, base=base)
    return pm


def bench_report(paths=None, base=None):
    """The bench.py lane: finding count + per-rule breakdown, so
    every BENCH report records the protocol-audit picture alongside
    the racelint concurrency numbers."""
    import time
    t0 = time.time()
    if paths is None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        paths = [os.path.join(repo, "paddle_tpu")]
        base = repo
    findings = lint_package(paths, base=base)
    breakdown = {}
    for f in findings:
        breakdown[f.code] = breakdown.get(f.code, 0) + 1
    return {
        "protolint_finding_count": len(findings),
        "protolint_rule_breakdown": dict(sorted(breakdown.items())),
        "protolint_elapsed_s": round(time.time() - t0, 2),
    }
