"""kv_model — protolint's whole-package world model of coordination-KV
usage (the PLxxx family's substrate; rules live in :mod:`proto_rules`).

racelint's :mod:`lock_model` answers "which locks does this package
take, from which threads, in what order"; this module answers the
multi-process analogue for the coordination key-value store: **which
keys does the package construct, from which process roles, with what
set/get/delete lifecycle** — so the PL rules can audit the seven
hand-rolled KV protocols (fleet wire/disagg/server, the `_coord_*`
collectives, elastic heartbeats, sentinel votes, resilience.fleet)
without executing any of them.

Three ideas, mirroring lock_model's shape:

- **Key identity is the construction site.**  Every key the package
  ever writes is built by an f-string (or a tiny helper returning
  one), so a symbolic evaluation of the key expression yields a
  *pattern*: literal segments kept, interpolated values replaced by
  placeholders named from the expression (``rank``-ish names →
  ``<rank>``, ``seq``/``round``/``step`` → ``<seq>``, namespace
  producers → ``<ns>``, else ``<v>``).  ``f"{ns}/serve/r{rank}/req/
  {seq}"`` becomes ``<ns>/serve/r<rank>/req/<seq>`` — the same
  identity :mod:`kv_tracer` recovers from concrete runtime keys, so
  the static model and the dynamic event streams cross-check.
- **Ops flow through wrappers.**  The sanctioned primitives
  (``key_value_set*``, ``blocking_key_value_get*``,
  ``key_value_delete``, ``key_value_dir_get*`` and the bounded fleet
  helpers ``kv_get_bytes``/``kv_set_bytes``) are leaves; package
  functions that call them (``wire.post_request``, ``_coord_get`` …)
  are *wrappers* whose ops are expanded at each call site — so
  ``RemoteEngineClient.call`` is seen to set the req key, block on
  the rsp key, and delete it, in that order, under the caller's role.
- **Roles come from entry points.**  The way lock_model discovers
  thread roots from ``Thread(target=)``, this model classifies each
  function into a process role — ``controller`` (ServingFleet /
  RemoteEngineClient / disagg orchestration), ``replica-server``
  (ReplicaServer / run_replica), ``monitor`` (FleetMonitor /
  Heartbeat* / Watchdog) or ``worker`` (SPMD ranks: collectives,
  sentinel votes, checkpointer) — so PL104 can reason about *which
  process* blocks on a key *which other process* sets.

Pure stdlib (ast only, no jax import): cheap enough for the bench
lane and the lint_all gate.  Over-approximation is deliberate; the
checked-in baseline (tools/protolint_baseline.json) absorbs the
reviewed remainder.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field, replace

__all__ = [
    "KeyOp", "FuncRec", "LivenessPair", "PackageModel", "ModuleBuilder",
    "PRIMS", "canon", "covers", "normalize_concrete_key",
    "patterns_compatible", "role_of",
]


# ------------------------------------------------------ primitives
@dataclass(frozen=True)
class _Prim:
    kind: str                  # set | get | get_raw | delete | dir_get
    key_index: int             # positional index of the key argument
    timeout_index: int = -1    # positional index of the timeout, -1 none
    timeout_kw: str = ""
    overwrite: bool = False    # set always overwrites by construction


# The sanctioned KV surface.  Helper entries (key at index 1) take the
# client as their first argument; the rest are client methods (key at
# index 0).  Functions *named* like a primitive are treated as its
# implementation and never scanned — fleet.kv_get_bytes' interior
# slicing loop is the timeout machinery itself, not a protocol site.
PRIMS = {
    "key_value_set": _Prim("set", 0),
    "key_value_set_bytes": _Prim("set", 0),
    "kv_set_bytes": _Prim("set", 1, overwrite=True),
    "_kv_set_str": _Prim("set", 1, overwrite=True),
    "blocking_key_value_get": _Prim("get_raw", 0, 1, "timeout_in_ms"),
    "blocking_key_value_get_bytes": _Prim("get_raw", 0, 1,
                                          "timeout_in_ms"),
    "kv_get_bytes": _Prim("get", 1, 2, "timeout_s"),
    "key_value_delete": _Prim("delete", 0),
    "key_value_dir_get": _Prim("dir_get", 0),
    "key_value_dir_get_bytes": _Prim("dir_get", 0),
}

_OPAQUE = "<opaque>"

# names whose value is a bounded wait budget — a raw blocking get whose
# timeout expression references one of these is deadline-driven
_BOUNDED_NAME_RE = re.compile(
    r"timeout|deadline|remaining|slice|budget|expiry|_ms$|_s$", re.I)
_RAW_TIMEOUT_CAP_MS = 600_000   # constants above 10 min are "unbounded"

_ENVELOPE_KEYS = {"ok", "err", "error", "status", "type"}


# ------------------------------------------------------------ roles
# Ordered: first match wins.  Probed against "modname.Class.func".
_ROLE_RULES = (
    ("controller", re.compile(
        r"controller|servingfleet|remoteengine|router|disagg", re.I)),
    ("monitor", re.compile(r"monitor|watchdog|heartbeat", re.I)),
    ("replica-server", re.compile(r"server|replica", re.I)),
)


def role_of(modname, class_name, func_name):
    """Process-role of a function, from the entry-point naming
    conventions the serving/resilience layers follow (docs/
    protolint.md "Role discovery")."""
    probe = ".".join(p for p in (modname, class_name or "",
                                 func_name or "") if p)
    for role, rx in _ROLE_RULES:
        if rx.search(probe):
            return role
    return "worker"


# ------------------------------------------------- pattern algebra
def canon(pattern):
    """Collapse every placeholder-bearing segment to ``<*>`` — the
    identity under which static patterns and runtime keys compare."""
    return "/".join("<*>" if "<" in seg else seg
                    for seg in pattern.strip("/").split("/") if seg)


def _seg_match(a, b):
    return a == b or a == "<*>" or b == "<*>"


def covers(prefix_canon, key_canon):
    """True when a delete of `prefix_canon` reclaims keys of
    `key_canon` (the coordination service's ``key_value_delete`` has
    directory semantics: it removes the key and every child)."""
    p = prefix_canon.split("/")
    k = key_canon.split("/")
    return len(p) <= len(k) and all(_seg_match(a, b)
                                    for a, b in zip(p, k))


def patterns_compatible(static_canon, runtime_canon):
    """Segment-wise wildcard match between a model pattern and a
    normalized runtime key (kv_tracer's conformance direction)."""
    s = static_canon.split("/")
    r = runtime_canon.split("/")
    return len(s) == len(r) and all(_seg_match(a, b)
                                    for a, b in zip(s, r))


_NS_CONCRETE_RE = re.compile(r"^ptpu/[^/]+/g\d+(/|$)")
_SEG_RULES = (
    (re.compile(r"^\d+$"), "<seq>"),
    (re.compile(r"^r\d+$"), "r<rank>"),
    (re.compile(r"^s\d+$"), "s<seq>"),
    (re.compile(r"^g\d+$"), "g<seq>"),
    (re.compile(r"^h\d+$"), "h<id>"),
    (re.compile(r"^[0-9a-f]{6,}$"), "<id>"),
    (re.compile(r"^\d+\.\d+$"), "<v>"),
)


def normalize_concrete_key(key):
    """A concrete runtime key → the construction-site pattern shape
    (the tracer half of the shared identity: ``ptpu/ab12/g0/serve/r3/
    req/17`` → ``<ns>/serve/r<rank>/req/<seq>``-compatible)."""
    key = str(key).strip("/")
    m = _NS_CONCRETE_RE.match(key + "/")
    if m:
        rest = key.split("/", 3)
        key = "<ns>" + ("/" + rest[3] if len(rest) > 3 else "")
    segs = []
    for seg in key.split("/"):
        if seg == "<ns>":
            segs.append(seg)
            continue
        for rx, repl in _SEG_RULES:
            if rx.match(seg):
                seg = repl
                break
        segs.append(seg)
    return "/".join(segs)


# -------------------------------------------------------- records
@dataclass
class KeyOp:
    """One KV operation against one key pattern, at one source site."""
    kind: str                   # set | get | get_raw | delete | dir_get
    pattern: str                # display pattern (or <opaque>)
    path: str
    line: int
    col: int
    func: str                   # qualname of the *defining* function
    timed: bool = True          # gets: wait is deadline-bounded
    watchdog: bool = False      # gets: an abort/watchdog callback is
    #                             threaded through the same call
    overwrite: bool = False     # sets: overwrite-latest semantics
    envelope: bool = False      # sets: value carries an ok/err envelope
    in_except: bool = False
    shim: bool = False          # deletes: overwrite-compat fallback
    kv_param: str = ""          # kind-1 wrapper: key is this parameter
    seq_src: tuple = ()         # provenance of a <seq> slot, or ()

    @property
    def canon(self):
        return canon(self.pattern)

    @property
    def opaque(self):
        return self.pattern.startswith(_OPAQUE)


@dataclass
class FuncRec:
    """One function's protocol-relevant content: its own primitive
    ops plus calls into other package wrappers, in statement order."""
    node: object
    qualname: str
    name: str
    modname: str
    class_name: str
    path: str
    params: tuple = ()
    items: list = field(default_factory=list)   # ("op", KeyOp) |
    #                                             ("call", name, node)
    single_return: object = None                # key-helper body expr
    env: dict = field(default_factory=dict)
    local_assigns: dict = field(default_factory=dict)
    #   name -> [(lineno, is_const, is_augmented)] in source order
    called: bool = False        # expanded under some in-package caller

    @property
    def role(self):
        return role_of(self.modname, self.class_name, self.name)


@dataclass
class LivenessPair:
    """An (interval, deadline) constant pair from one config scope —
    PL105's input."""
    path: str
    line: int
    scope: str
    interval_name: str
    interval: float
    deadline_name: str
    deadline: float


@dataclass
class PatternInfo:
    canon: str
    display: str
    sets: list = field(default_factory=list)
    gets: list = field(default_factory=list)        # get + get_raw
    deletes: list = field(default_factory=list)     # non-shim
    dir_gets: list = field(default_factory=list)
    set_roles: set = field(default_factory=set)
    get_roles: set = field(default_factory=set)

    @property
    def ns_rooted(self):
        return any(op.pattern.startswith("<ns>") for op in self.sets)

    @property
    def seq_lane(self):
        return any("<seq>" in op.pattern for op in self.sets)


# ------------------------------------------------ name → placeholder
def _hint(name):
    n = name.lower().lstrip("_")
    if (n in ("ns", "namespace", "base", "prefix")
            or n.endswith("namespace") or n.endswith("_ns")):
        return "<ns>"
    if ("rank" in n or n in ("pid", "r", "src", "peer", "m", "i",
                             "member", "members", "grank", "host",
                             "src_global")):
        return "<rank>"
    if ("seq" in n or "round" in n or "step" in n
            or n in ("rnd", "idx", "old", "n")):
        return "<seq>"
    if n == "hid" or n.endswith("id") or "uuid" in n:
        return "<id>"
    return "<v>"


def _callee_name(func):
    """Bare name of a call target (last dotted segment)."""
    while isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _callee_base(func):
    """Qualifier of an attribute call (``wire`` in ``wire.f(...)``,
    ``self`` in ``self.f(...)``), else ''. """
    if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                      ast.Name):
        return func.value.id
    return ""


def _is_namespace_producer(func):
    name = _callee_name(func)
    return (name.endswith("namespace") or name == "coord_namespace"
            or name == "_ns")


class _KeyEval:
    """Symbolic evaluation of a key expression into display patterns.

    Returns a list of ``(pattern, seq_src)`` — usually one element;
    a For-loop binding over a literal tuple (the ``_coord_reap``
    two-prefix sweep) yields one per binding.  Empty when the
    expression is outside the supported shape (caller records an
    opaque op)."""

    _MAX_DEPTH = 8
    _MAX_BRANCH = 4

    def __init__(self, model, func):
        self.model = model
        self.func = func

    # -- public -----------------------------------------------------
    def eval_key(self, node):
        out = self._eval(node, 0)
        return [(p.rstrip("/"), src) for p, src in out if p]

    # -- internals --------------------------------------------------
    def _eval(self, node, depth):
        """→ [(pattern, seq_src)]"""
        if depth > self._MAX_DEPTH:
            return []
        if isinstance(node, ast.Constant) and isinstance(node.value,
                                                         str):
            return [(node.value, ())]
        if isinstance(node, ast.JoinedStr):
            return self._joined(node, depth)
        if isinstance(node, ast.Name):
            bindings = self.func.env.get(node.id)
            if bindings:
                out = []
                for b in bindings[:self._MAX_BRANCH]:
                    out.extend(self._eval(b, depth + 1))
                if out:
                    return out
            if node.id in self.func.params:
                frag, src = self._fragment(node, depth)
                return [(frag, src)] if frag else []
            return []
        if isinstance(node, ast.Call):
            name = _callee_name(node.func)
            if name in ("str", "format", "int", "float", "abs") \
                    and node.args:
                return self._eval(node.args[0], depth + 1)
            if _is_namespace_producer(node.func):
                return [("<ns>", ())]
            helper = self.model.resolve_helper(name, self.func,
                                              _callee_base(node.func))
            if helper is not None:
                inner = _KeyEval(self.model, _helper_scope(helper,
                                                           self.func))
                return inner._eval(helper.single_return, depth + 1)
            return []
        if isinstance(node, ast.IfExp):
            return (self._eval(node.body, depth + 1)
                    + self._eval(node.orelse, depth + 1))
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            left = self._eval(node.left, depth + 1)
            right = self._eval(node.right, depth + 1)
            return [(a + b, sa or sb) for a, sa in left[:2]
                    for b, sb in right[:2]]
        if isinstance(node, ast.Attribute):
            frag, src = self._fragment(node, depth)
            return [(frag, src)] if frag else []
        return []

    def _joined(self, node, depth):
        outs = [("", ())]
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                outs = [(p + str(piece.value), s) for p, s in outs]
                continue
            if not isinstance(piece, ast.FormattedValue):
                return []
            frags = self._fragments(piece.value, depth)
            if not frags:
                return []
            outs = [(p + f, s or fs) for p, s in outs
                    for f, fs in frags[:self._MAX_BRANCH]]
            if len(outs) > self._MAX_BRANCH:
                outs = outs[:self._MAX_BRANCH]
        return outs

    def _fragments(self, node, depth):
        """An interpolated value → [(text fragment, seq_src)]."""
        if depth > self._MAX_DEPTH:
            return [("<v>", ())]
        if isinstance(node, ast.Name):
            bindings = self.func.env.get(node.id)
            # an int-constant binding (``seq = 0`` before the loop's
            # ``seq += 1``) is a COUNTER SEED, not the key's value —
            # keep the name's placeholder, don't bake in the literal
            if bindings and node.id not in self.func.params and not \
                    all(isinstance(b, ast.Constant)
                        and isinstance(b.value, (int, float))
                        for b in bindings):
                out = []
                for b in bindings[:self._MAX_BRANCH]:
                    out.extend(self._fragments(b, depth + 1))
                if out:
                    return out
            frag, src = self._fragment(node, depth)
            return [(frag, src)]
        full = self._eval(node, depth + 1)
        if full:
            return full
        frag, src = self._fragment(node, depth)
        return [(frag, src)]

    def _fragment(self, node, depth):
        """One placeholder (with <seq> provenance when derivable)."""
        if isinstance(node, ast.Constant):
            return str(node.value), ()
        if isinstance(node, ast.Name):
            h = _hint(node.id)
            src = ()
            if h == "<seq>":
                src = (("param", self.func.qualname, node.id)
                       if node.id in self.func.params
                       else ("local", self.func.qualname, node.id))
            return h, src
        if isinstance(node, ast.Attribute):
            h = ("<ns>" if node.attr.endswith("namespace")
                 else _hint(node.attr))
            src = ()
            if (h == "<seq>" and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                src = ("attr", self.func.class_name, node.attr)
            return h, src
        if isinstance(node, ast.Subscript):
            base = node.value
            if isinstance(base, ast.Name):
                h = _hint(base.id)
                src = ()
                if (h == "<seq>" and base.id
                        in self.model.module_globals.get(
                            self.func.modname, ())):
                    src = ("global", self.func.modname, base.id)
                return h, src
            if isinstance(base, ast.Attribute):
                return _hint(base.attr), ()
            return "<v>", ()
        if isinstance(node, ast.BinOp):
            return self._fragment(node.left, depth + 1)
        return "<v>", ()


def _helper_scope(helper, caller):
    """Evaluation scope for inlining a key helper: the helper's own
    params (mapped to name hints) see through to the CALLER's env for
    closure variables (sentinel's nested ``key_for`` reads ``ns`` /
    ``site`` from ``digest_vote``'s scope)."""
    merged_env = dict(caller.env)
    merged_env.update(helper.env)
    return replace(helper, env=merged_env)


# -------------------------------------------------- module builder
class ModuleBuilder:
    """AST pass over one module: function records, env maps, module
    globals, attribute-assignment index (PL202), liveness constants
    (PL105)."""

    def __init__(self, path, modname, tree):
        self.path = path
        self.modname = modname
        self.tree = tree
        self.funcs = []
        self.globals = set()
        self.attr_assigns = {}     # (class, attr) -> [(method, lineno,
        #                             const, augmented)]
        self.global_assigns = {}   # global name -> [(func, lineno)]
        #   const stores into a module-global container
        #   (``_COORD_ROUND[0] = 0``) — PL202's reset evidence
        self.liveness = []
        self.import_aliases = {}   # alias -> dotted module

    def build(self):
        for node in self.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.globals.add(t.id)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                self._imports(node)
        self._walk(self.tree, class_name="", qual_prefix="")
        return self

    def _imports(self, node):
        if isinstance(node, ast.Import):
            for a in node.names:
                self.import_aliases[a.asname or a.name.split(".")[0]] \
                    = a.name
        else:
            mod = node.module or ""
            for a in node.names:
                self.import_aliases[a.asname or a.name] = \
                    f"{mod}.{a.name}" if mod else a.name

    def _walk(self, node, class_name, qual_prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._walk(child, class_name=child.name,
                           qual_prefix=f"{qual_prefix}{child.name}.")
                self._liveness_scan(child)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                self._func(child, class_name, qual_prefix)
                # nested defs (sentinel's key_for) and methods
                self._walk(child, class_name,
                           f"{qual_prefix}{child.name}.")
            else:
                self._walk(child, class_name, qual_prefix)

    # -- functions ---------------------------------------------------
    def _func(self, node, class_name, qual_prefix):
        if node.name in PRIMS:
            return      # the sanctioned implementation, not a user
        a = node.args
        params = tuple(x.arg for x in (a.posonlyargs + a.args
                                       + a.kwonlyargs))
        rec = FuncRec(node=node,
                      qualname=f"{self.modname}.{qual_prefix}"
                               f"{node.name}",
                      name=node.name, modname=self.modname,
                      class_name=class_name, path=self.path,
                      params=params)
        rec.env = self._env(node, rec.local_assigns)
        # a key helper may carry a docstring and a lazy import above
        # its return (elastic._hb_prefix) — neither changes the key
        body = [s for s in node.body
                if not (isinstance(s, ast.Expr)
                        and isinstance(s.value, ast.Constant))
                and not isinstance(s, (ast.Import, ast.ImportFrom))]
        if (len(body) == 1 and isinstance(body[0], ast.Return)
                and body[0].value is not None):
            rec.single_return = body[0].value
        self._collect(node, rec)
        if class_name:
            self._attr_scan(node, class_name)
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Subscript)
                    and isinstance(sub.targets[0].value, ast.Name)
                    and isinstance(sub.value, ast.Constant)
                    and sub.targets[0].value.id in self.globals):
                self.global_assigns.setdefault(
                    sub.targets[0].value.id, []).append(
                    (node.name, sub.lineno))
        self.funcs.append(rec)

    def _env(self, node, local_assigns):
        """name → [bound exprs] from Assigns and literal-tuple For
        targets, for this function's DIRECT body (nested defs keep
        their own env); `local_assigns` gains the source-ordered
        assignment log PL202's local-counter check reads."""
        env = {}

        def visit(n):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                if isinstance(child, ast.Assign):
                    for t in child.targets:
                        if isinstance(t, ast.Name):
                            env.setdefault(t.id, []).append(child.value)
                            local_assigns.setdefault(t.id, []).append(
                                (child.lineno,
                                 isinstance(child.value, ast.Constant),
                                 False))
                elif isinstance(child, ast.AugAssign) and isinstance(
                        child.target, ast.Name):
                    local_assigns.setdefault(
                        child.target.id, []).append(
                        (child.lineno, False, True))
                elif isinstance(child, ast.For) and isinstance(
                        child.target, ast.Name) and isinstance(
                        child.iter, (ast.Tuple, ast.List)):
                    env.setdefault(child.target.id, []).extend(
                        child.iter.elts)
                visit(child)

        visit(node)
        return env

    def _collect(self, node, rec):
        """Ordered (op|call) items, with except-handler context."""
        items = []

        def visit(n, except_of):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                ctx = except_of
                if isinstance(child, ast.ExceptHandler):
                    ctx = n        # the owning Try node
                if isinstance(child, ast.Call):
                    items.append((child, ctx))
                visit(child, ctx)

        def walk_try_aware(n, except_of):
            # ast.iter_child_nodes on a Try yields body stmts then
            # handlers; the recursion above flags handler bodies via
            # the ExceptHandler hop
            visit(n, except_of)

        walk_try_aware(node, None)
        items.sort(key=lambda it: (it[0].lineno, it[0].col_offset))
        for call, try_node in items:
            name = _callee_name(call.func)
            if name in PRIMS:
                rec.items.append(
                    ("op", self._prim_op(call, name, rec, try_node)))
            elif name:
                rec.items.append(("call", name, call))

    def _prim_op(self, call, name, rec, try_node):
        prim = PRIMS[name]
        key_node = self._arg(call, prim.key_index, "key")
        evaluator = _KeyEval(_ModelView(self), rec)
        patterns = (evaluator.eval_key(key_node)
                    if key_node is not None else [])
        if not patterns:
            patterns = [(f"{_OPAQUE}:{rec.qualname}", ())]
        pattern, seq_src = patterns[0]
        op = KeyOp(kind=prim.kind, pattern=pattern, path=self.path,
                   line=call.lineno, col=call.col_offset,
                   func=rec.qualname, seq_src=seq_src)
        op._alt_patterns = [p for p, _ in patterns[1:]]
        # cross-module key helpers (disagg's wire.handoff_key) can't
        # resolve until the whole package is loaded — keep the AST so
        # PackageModel.finalize can retry opaque evaluations
        op._key_node = key_node
        op._rec = rec
        if (key_node is not None and isinstance(key_node, ast.Name)
                and key_node.id in rec.params):
            op.kv_param = key_node.id
        if prim.kind == "set":
            op.overwrite = prim.overwrite or any(
                kw.arg == "allow_overwrite" for kw in call.keywords)
            value_node = self._arg(call, prim.key_index + 1, "value")
            op.envelope = self._has_envelope(value_node, rec)
        if prim.kind == "get_raw":
            op.timed = self._raw_timed(call, prim)
            op.watchdog = any(
                re.search(r"abort|watchdog", sub.id if isinstance(
                    sub, ast.Name) else sub.attr, re.I) is not None
                for sub in ast.walk(call)
                if isinstance(sub, (ast.Name, ast.Attribute)))
        if prim.kind == "delete":
            op.in_except = try_node is not None
            if try_node is not None:
                op.shim = self._is_shim(try_node, op, rec)
        return op

    def _arg(self, call, index, kwname):
        if index < len(call.args):
            a = call.args[index]
            return None if isinstance(a, ast.Starred) else a
        for kw in call.keywords:
            if kw.arg == kwname:
                return kw.value
        return None

    def _raw_timed(self, call, prim):
        node = None
        if prim.timeout_index < len(call.args):
            node = call.args[prim.timeout_index]
        else:
            for kw in call.keywords:
                if kw.arg == prim.timeout_kw:
                    node = kw.value
        if node is None:
            return False
        if isinstance(node, ast.Constant):
            try:
                return 0 < float(node.value) <= _RAW_TIMEOUT_CAP_MS
            except (TypeError, ValueError):
                return False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and _BOUNDED_NAME_RE.search(
                    sub.id):
                return True
            if isinstance(sub, ast.Attribute) and \
                    _BOUNDED_NAME_RE.search(sub.attr):
                return True
        return False

    def _has_envelope(self, value_node, rec):
        if value_node is None:
            return False
        seen = [value_node]
        for sub in ast.walk(value_node):
            if isinstance(sub, ast.Name):
                seen.extend(rec.env.get(sub.id, ()))
        for root in seen:
            for sub in ast.walk(root):
                if isinstance(sub, ast.Dict):
                    keys = {k.value for k in sub.keys
                            if isinstance(k, ast.Constant)}
                    if keys & _ENVELOPE_KEYS:
                        return True
        return False

    def _is_shim(self, try_node, delete_op, rec):
        """A delete in an except handler whose try body SETS the same
        pattern is the allow_overwrite compatibility fallback — not a
        lifecycle delete."""
        evaluator = _KeyEval(_ModelView(self), rec)
        for stmt in getattr(try_node, "body", ()):
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                name = _callee_name(sub.func)
                prim = PRIMS.get(name)
                if prim is None or prim.kind != "set":
                    continue
                key_node = self._arg(sub, prim.key_index, "key")
                if key_node is None:
                    continue
                for p, _src in evaluator.eval_key(key_node):
                    if canon(p) == delete_op.canon:
                        return True
        return False

    # -- PL202 index -------------------------------------------------
    def _attr_scan(self, node, class_name):
        for sub in ast.walk(node):
            target = None
            augmented = False
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                target = sub.targets[0]
                const = isinstance(sub.value, ast.Constant)
            elif isinstance(sub, ast.AugAssign):
                target = sub.target
                const = False
                augmented = True
            else:
                continue
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                self.attr_assigns.setdefault(
                    (class_name, target.attr), []).append(
                    (node.name, sub.lineno, const, augmented))

    # -- PL105 constants ---------------------------------------------
    _INTERVAL_RE = re.compile(r"interval", re.I)
    _DEADLINE_RE = re.compile(r"stale|suspect|(^|_)dead", re.I)

    def _liveness_scan(self, cls):
        init = next((m for m in cls.body
                     if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is None:
            return
        scope = {}
        a = init.args
        pos = a.posonlyargs + a.args
        defaults = a.defaults
        for arg, dflt in zip(pos[len(pos) - len(defaults):], defaults):
            v = self._const(dflt, scope)
            if v is not None:
                scope[arg.arg] = v
        for stmt in init.body:
            if (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Attribute)
                    and isinstance(stmt.targets[0].value, ast.Name)
                    and stmt.targets[0].value.id == "self"):
                v = self._const(stmt.value, scope)
                if v is not None:
                    scope[stmt.targets[0].attr] = v
        intervals = [(k, v) for k, v in scope.items()
                     if self._INTERVAL_RE.search(k) and v > 0]
        deadlines = [(k, v) for k, v in scope.items()
                     if self._DEADLINE_RE.search(k) and v > 0]
        for iname, ival in intervals:
            for dname, dval in deadlines:
                self.liveness.append(LivenessPair(
                    path=self.path, line=cls.lineno, scope=cls.name,
                    interval_name=iname, interval=ival,
                    deadline_name=dname, deadline=dval))

    def _const(self, node, scope, depth=0):
        if depth > 6 or node is None:
            return None
        if isinstance(node, ast.Constant):
            return (float(node.value)
                    if isinstance(node.value, (int, float))
                    and not isinstance(node.value, bool) else None)
        if isinstance(node, ast.Name):
            return scope.get(node.id)
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name) and node.value.id == "self":
            return scope.get(node.attr)
        if isinstance(node, ast.IfExp):
            v = self._const(node.body, scope, depth + 1)
            return v if v is not None else self._const(node.orelse,
                                                      scope, depth + 1)
        if isinstance(node, ast.BinOp):
            left = self._const(node.left, scope, depth + 1)
            right = self._const(node.right, scope, depth + 1)
            if left is None or right is None:
                return None
            try:
                if isinstance(node.op, ast.Mult):
                    return left * right
                if isinstance(node.op, ast.Div):
                    return left / right
                if isinstance(node.op, ast.Add):
                    return left + right
                if isinstance(node.op, ast.Sub):
                    return left - right
            except ZeroDivisionError:
                return None
            return None
        if isinstance(node, ast.Call):
            name = _callee_name(node.func)
            if name in ("_env_float", "_env_int") and len(node.args) \
                    >= 2:
                return self._const(node.args[1], scope, depth + 1)
            if name in ("float", "int", "abs") and node.args:
                return self._const(node.args[0], scope, depth + 1)
            if name in ("min", "max") and node.args:
                vals = [self._const(x, scope, depth + 1)
                        for x in node.args]
                if all(v is not None for v in vals):
                    return (min if name == "min" else max)(vals)
            return None
        return None


class _ModelView:
    """Helper-resolution view a ModuleBuilder hands its evaluators
    before the PackageModel exists (same-module helpers only at build
    time; the PackageModel swaps in cross-module resolution)."""

    def __init__(self, builder):
        self.builder = builder
        self.module_globals = {builder.modname: builder.globals}

    def resolve_helper(self, name, caller, base):
        for f in self.builder.funcs:
            if f.name == name and f.single_return is not None:
                return f
        return None


# --------------------------------------------------- package model
class PackageModel:
    def __init__(self):
        self.modules = []            # ModuleBuilder
        self.funcs = []
        self.module_globals = {}
        self.global_const_assigns = {}   # (modname, global) ->
        #                                  [(func, lineno)]
        self.attr_assigns = {}
        self.liveness_pairs = []
        self._by_name = {}
        self._by_mod = {}
        self._expanded = {}
        self.pattern_table = {}

    def add(self, builder):
        self.modules.append(builder)
        self.funcs.extend(builder.funcs)
        self.module_globals[builder.modname] = builder.globals
        for k, v in builder.global_assigns.items():
            self.global_const_assigns.setdefault(
                (builder.modname, k), []).extend(v)
        for k, v in builder.attr_assigns.items():
            self.attr_assigns.setdefault(k, []).extend(v)
        self.liveness_pairs.extend(builder.liveness)

    # -- helper / wrapper resolution --------------------------------
    def resolve_helper(self, name, caller, base):
        """A key-construction helper (single-return function) by bare
        name: same module first, then an import-alias-qualified
        module, then anywhere unique."""
        f = self._lookup(name, caller, base)
        return f if f is not None and f.single_return is not None \
            else None

    def _lookup(self, name, caller, base):
        mod = self._by_mod.get(caller.modname, {})
        if name in mod:
            return mod[name]
        if base and base not in ("self", "cls"):
            builder = next((b for b in self.modules
                            if b.modname == caller.modname), None)
            alias = (builder.import_aliases.get(base, "")
                     if builder else "")
            if alias:
                for m, table in self._by_mod.items():
                    if (m == alias or m.endswith("." + alias)
                            or alias.endswith(m)) and name in table:
                        return table[name]
        cands = self._by_name.get(name, ())
        return cands[0] if len(cands) == 1 else None

    # -- finalize ----------------------------------------------------
    def finalize(self):
        for f in self.funcs:
            self._by_mod.setdefault(f.modname, {})[f.name] = f
            self._by_name.setdefault(f.name, []).append(f)
        self._reeval_opaque()
        for f in self.funcs:
            self.expanded_ops(f)
        self._build_pattern_table()
        return self

    def _reeval_opaque(self):
        """Retry key evaluation for ops that needed a helper from
        another module (build time only sees one module at a time)."""
        for f in self.funcs:
            for item in f.items:
                if item[0] != "op":
                    continue
                op = item[1]
                node = getattr(op, "_key_node", None)
                if not op.opaque or node is None:
                    continue
                patterns = _KeyEval(self, op._rec).eval_key(node)
                if patterns:
                    op.pattern, op.seq_src = patterns[0]
                    op._alt_patterns = [p for p, _ in patterns[1:]]

    def expanded_ops(self, func):
        """The function's KV ops with package wrappers expanded at
        their call sites (key-parameter substitution for kind-1
        wrappers), in statement order."""
        return self._expand(func, frozenset())

    def _expand(self, func, stack):
        key = func.qualname
        if key in self._expanded:
            return self._expanded[key]
        if key in stack:
            return []
        out = []
        for item in func.items:
            if item[0] == "op":
                out.append(item[1])
                continue
            _tag, name, call = item
            callee = self._lookup(name, func, _callee_base(call.func))
            if callee is None or callee is func:
                continue
            inner = self._expand(callee, stack | {key})
            if not inner:
                continue
            callee.called = True
            for op in inner:
                out.append(self._substitute(op, callee, call, func))
        self._expanded[key] = out
        return out

    def _substitute(self, op, callee, call, caller):
        if not op.kv_param:
            return op
        arg = self._bound_arg(call, callee, op.kv_param)
        if arg is None:
            return replace(op, kv_param="",
                           pattern=f"{_OPAQUE}:{caller.qualname}")
        if isinstance(arg, ast.Name) and arg.id in caller.params \
                and arg.id not in caller.env:
            return replace(op, kv_param=arg.id)   # re-parameterize
        patterns = _KeyEval(self, caller).eval_key(arg)
        if not patterns:
            return replace(op, kv_param="",
                           pattern=f"{_OPAQUE}:{caller.qualname}")
        pattern, seq_src = patterns[0]
        return replace(op, kv_param="", pattern=pattern,
                       seq_src=seq_src or op.seq_src)

    def _bound_arg(self, call, callee, param):
        try:
            idx = callee.params.index(param)
        except ValueError:
            return None
        # methods are called without their `self` slot
        if callee.class_name and callee.params \
                and callee.params[0] in ("self", "cls"):
            idx -= 1
        if 0 <= idx < len(call.args):
            a = call.args[idx]
            return None if isinstance(a, ast.Starred) else a
        for kw in call.keywords:
            if kw.arg == param:
                return kw.value
        return None

    # -- aggregation -------------------------------------------------
    def top_funcs(self):
        """Functions that are not expanded under an in-package caller
        — the per-role op sequences PL104/PL201 reason over."""
        return [f for f in self.funcs if not f.called]

    def _build_pattern_table(self):
        table = {}
        seen = set()
        for f in self.top_funcs():
            role = f.role
            for op in self.expanded_ops(f):
                if op.opaque:
                    continue
                for pattern in [op.pattern] + getattr(
                        op, "_alt_patterns", []):
                    c = canon(pattern)
                    info = table.get(c)
                    if info is None:
                        info = table[c] = PatternInfo(canon=c,
                                                      display=pattern)
                    dedupe = (op.path, op.line, op.col, op.kind,
                              pattern, role)
                    if dedupe in seen:
                        continue
                    seen.add(dedupe)
                    this = (replace(op, pattern=pattern)
                            if pattern != op.pattern else op)
                    if op.kind == "set":
                        info.sets.append(this)
                        info.set_roles.add(role)
                    elif op.kind in ("get", "get_raw"):
                        info.gets.append(this)
                        info.get_roles.add(role)
                    elif op.kind == "delete":
                        if not op.shim:
                            info.deletes.append(this)
                    elif op.kind == "dir_get":
                        info.dir_gets.append(this)
        self.pattern_table = table

    # -- queries the rules use --------------------------------------
    def all_deletes(self):
        for info in self.pattern_table.values():
            for op in info.deletes:
                yield op

    def delete_covers(self, pattern_canon, include_root=False):
        """Non-shim deletes reclaiming keys of `pattern_canon`; the
        bare-namespace root reap (``<*>``) is the end-of-run backstop,
        not a lifecycle policy, and excluded by default."""
        out = []
        for op in self.all_deletes():
            if not include_root and op.canon == "<*>":
                continue
            if covers(op.canon, pattern_canon):
                out.append(op)
        return out

    def dir_get_covers(self, pattern_canon):
        out = []
        for info in self.pattern_table.values():
            for op in info.dir_gets:
                if covers(op.canon, pattern_canon):
                    out.append(op)
        return out
