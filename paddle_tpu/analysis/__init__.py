"""paddle_tpu.analysis — tracelint: trace-safety & TPU-compilability lint.

Two passes over user code headed for the whole-program XLA path:

- **AST pass** (pure stdlib, no trace): walks every function reachable
  from a `@to_static` entry and reports, with file:line and a TLxxx
  code, hazards the converter can otherwise only raise on at trace
  time — constructs outside the conversion subset (TL0xx), host syncs
  and trace-time side effects (TL1xx), recompile-storm hazards (TL3xx).
- **jaxpr pass** (post-trace): lints the emitted program — f64
  promotions, large baked constants, collectives vs the mesh (TL4xx).

Surfaces: `tools/tracelint.py` (CLI, baseline-aware `--check` mode) and
`paddle_tpu.jit.to_static(check=True)` (warnings at wrap/compile time).
Per-line suppression: `# tracelint: disable=TL101`; whole file:
`# tracelint: skip-file`.

Siblings sharing the rule registry, the Finding/baseline machinery
(`analysis/common.py`) and the suppression syntax: **shardlint**
(`shard_rules.py`/`cost_audit.py`, SLxxx over traced jaxprs — see
`tools/shardlint.py`), **racelint** (`lock_model.py`/`race_rules.py`,
RLxxx host-runtime concurrency audit, plus the runtime lock-order
sanitizer in `lock_tracer.py` — see `tools/racelint.py`) and
**numlint** (`dtype_flow.py`/`num_rules.py`, NLxxx numerics &
precision-flow audit over traced jaxprs — see `tools/numlint.py` and
docs/numlint.md) and **kernlint** (`kernel_rules.py`/`vmem_model.py`,
KLxxx audit of Pallas kernel interiors — tile alignment, VMEM budgets,
grid coverage, in-kernel numerics; see `tools/kernlint.py` and
docs/kernlint.md) and **protolint** (`kv_model.py`/`proto_rules.py`,
PLxxx audit of the cross-process coordination-KV protocols — key
lifecycle, wait boundedness, role cycles, liveness budgets, error
envelopes — plus the runtime KV event tracer in `kv_tracer.py` the
chaos suite cross-checks the model against; see `tools/protolint.py`
and docs/protolint.md).
"""
from __future__ import annotations

import inspect
import textwrap
import warnings

from paddle_tpu.analysis.rules import (  # noqa: F401
    RULES, TraceHazardError, message_for,
)
from paddle_tpu.analysis.visitor import (  # noqa: F401
    Finding, iter_py_files, lint_source, rel_path,
)
from paddle_tpu.analysis.subset_rules import check_recompile, check_subset
from paddle_tpu.analysis.purity_rules import check_purity
from paddle_tpu.analysis.shard_rules import (  # noqa: F401
    AuditConfig, InputInfo, MeshInfo, input_infos_from_state,
)
from paddle_tpu.analysis.cost_audit import CostReport  # noqa: F401
from paddle_tpu.analysis import report  # noqa: F401


def __getattr__(name):
    # NumConfig lazily (num_rules imports nothing heavy, but keeping the
    # light-import surface of this package flat is the house rule)
    if name == "NumConfig":
        from paddle_tpu.analysis.num_rules import NumConfig
        return NumConfig
    if name == "KernelConfig":
        from paddle_tpu.analysis.kernel_rules import KernelConfig
        return KernelConfig
    raise AttributeError(name)

__all__ = [
    "RULES", "TraceHazardError", "Finding", "TracelintWarning",
    "ShardlintWarning", "NumlintWarning", "KernlintWarning",
    "lint_paths", "lint_file", "lint_callable", "check_jaxpr",
    "audit_jaxpr", "check_numerics", "check_kernels",
    "check_kernel_files", "message_for", "report", "AuditConfig",
    "MeshInfo", "InputInfo", "CostReport", "NumConfig", "KernelConfig",
    "input_infos_from_state",
]

AST_RULE_SETS = (check_subset, check_purity, check_recompile)


class TracelintWarning(UserWarning):
    """Emitted by to_static(check=True) for each tracelint finding."""


class ShardlintWarning(TracelintWarning):
    """Emitted by to_static(audit=True) for each shardlint finding.
    Subclasses TracelintWarning so one warning filter governs both."""


class NumlintWarning(TracelintWarning):
    """Emitted by to_static(check=True) for each numlint (NLxxx)
    finding, alongside the TL4xx jaxpr pass.  Subclasses
    TracelintWarning so one warning filter governs the whole family."""


class KernlintWarning(TracelintWarning):
    """Emitted by to_static(check=True) for each kernlint (KLxxx)
    finding over the program's ``pallas_call`` interiors.  Subclasses
    TracelintWarning so one warning filter governs the whole family."""


def lint_file(path, base=None, rule_sets=AST_RULE_SETS):
    try:
        with open(path, encoding="utf-8", errors="replace") as fh:
            source = fh.read()
    except OSError:
        return []
    return lint_source(path, source, rule_sets, base=base)


def lint_paths(paths, base=None, rule_sets=AST_RULE_SETS):
    """AST-lint every .py file under `paths`; returns [Finding]."""
    findings = []
    for p in iter_py_files(paths):
        findings.extend(lint_file(p, base=base, rule_sets=rule_sets))
    return findings


def lint_callable(fn, rule_sets=AST_RULE_SETS):
    """AST-lint one function (a to_static target) and its module-local
    reach. Used by `to_static(check=True)`; returns [] when source is
    unavailable (builtins, REPL, exec'd code)."""
    fn = inspect.unwrap(fn)
    if inspect.ismethod(fn):
        fn = fn.__func__
    try:
        path = inspect.getsourcefile(fn)
        source = inspect.getsource(inspect.getmodule(fn))
    except (OSError, TypeError):
        # no module source (REPL) — fall back to the function body alone
        try:
            path = "<%s>" % getattr(fn, "__qualname__", "fn")
            source = textwrap.dedent(inspect.getsource(fn))
        except (OSError, TypeError):
            return []
    firstline = fn.__code__.co_firstlineno

    def select_roots(index):
        cands = [fi for fi in index.functions if fi.node.name == fn.__name__]
        if not cands:
            return []
        root = min(cands, key=lambda fi: abs(fi.node.lineno - firstline))
        # the wrapped function IS a to_static entry even when wrapped in
        # call form (to_static(fn, check=True)) — entry-only rules
        # (TL301 mutable static args) must see it as one
        root.is_entry = True
        return [root]

    return lint_source(path, source, rule_sets, select_roots=select_roots)


def check_jaxpr(closed_jaxpr, where="<traced function>", **kw):
    """Post-trace jaxpr lint (TL4xx). Lazy import: jax only loads here."""
    from paddle_tpu.analysis.jaxpr_rules import check_jaxpr as _impl
    return _impl(closed_jaxpr, where=where, **kw)


def check_numerics(closed_jaxpr, where="<traced program>", inputs=None,
                   config=None, suppress=True):
    """numlint: the NL-rule numerics & precision-flow audit of one
    traced program (see analysis/num_rules.py).  Lazy import so the
    light CLI path never pays for it."""
    from paddle_tpu.analysis.num_rules import check_numerics as _impl
    return _impl(closed_jaxpr, where=where, inputs=inputs, config=config,
                 suppress=suppress)


def check_kernels(closed_jaxpr, where="<traced program>", config=None,
                  suppress=True):
    """kernlint: the KL-rule audit of every Pallas kernel interior
    reachable from one traced program (see analysis/kernel_rules.py).
    Lazy import so the light CLI path never pays for it."""
    from paddle_tpu.analysis.kernel_rules import check_kernels as _impl
    return _impl(closed_jaxpr, where=where, config=config,
                 suppress=suppress)


def check_kernel_files(paths=None):
    """kernlint AST pass: trace-free KL lint over Pallas kernel sources
    (defaults to ``paddle_tpu/ops/pallas/*.py``)."""
    from paddle_tpu.analysis.kernel_rules import check_kernel_files as _impl
    return _impl(paths)


def audit_jaxpr(closed_jaxpr, where="<traced program>", inputs=None,
                mesh=None, config=None, suppress=True):
    """shardlint: the full SL-rule audit of one traced program.

    Runs the sharding pass (SL1xx), the collective-safety pass (SL2xx)
    and the memory/layout cost pass (SL3xx) over `closed_jaxpr`;
    returns ``(findings, CostReport)``.

    - `inputs`: [InputInfo] aligned with the jaxpr invars (use
      :func:`input_infos_from_state` for a to_static state list, or
      :meth:`StaticFunction.traced_program` which returns both).
    - `mesh`: a MeshInfo / jax Mesh / None (falls back to the installed
      global mesh).  Pass ``MeshInfo.of(axes={"dp": 8})`` to audit a
      CPU-traced program against a hypothetical production topology.
    - `suppress`: apply per-line `# tracelint: disable=SLxxx` comments
      at each finding's resolved source site.
    """
    from paddle_tpu.analysis import cost_audit, shard_rules
    config = config or shard_rules.AuditConfig()
    mesh = mesh if isinstance(mesh, shard_rules.MeshInfo) \
        else shard_rules.MeshInfo.of(mesh)
    findings = shard_rules.check_sharding(
        closed_jaxpr, inputs=inputs, mesh=mesh, config=config, where=where)
    findings += shard_rules.check_collectives(
        closed_jaxpr, mesh=mesh, config=config, where=where)
    mem_findings, rep = cost_audit.audit_memory(
        closed_jaxpr, where=where, inputs=inputs, config=config)
    findings += mem_findings
    if suppress:
        findings = shard_rules.apply_suppressions(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings, rep


def warn_findings(findings, stacklevel=3, category=None, prefix="tracelint"):
    for f in findings:
        warnings.warn(f"{prefix}: {f.format()}",
                      category or TracelintWarning, stacklevel=stacklevel)
