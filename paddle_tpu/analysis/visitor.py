"""tracelint visitor framework: findings, suppressions, reachability.

The AST pass mirrors what the runtime will do: `@to_static` wraps an
entry function, and `convert_call` recursively converts every function
and `Layer.forward` the entry reaches.  Statically we approximate that
reach *within one module*: entries are (a) functions carrying a
`to_static` decorator (any dotted spelling) and (b) `forward` methods of
classes defined in the module (convert_call transforms those when a
layer is called from traced code).  From each entry we close over
module-local calls — `f(...)` resolving to a module/enclosing-scope
`def`, and `self.m(...)` resolving to a method of the enclosing class.

Pure stdlib — no jax / paddle_tpu imports — so the CLI can lint a tree
in milliseconds without touching the framework.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field


@dataclass
class Finding:
    path: str          # repo-relative, posix separators
    line: int
    col: int
    code: str
    message: str
    source_line: str = ""

    def format(self):
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"

    def to_dict(self):
        return {"path": self.path, "line": self.line, "col": self.col,
                "code": self.code, "message": self.message,
                "source_line": self.source_line}


# one suppression syntax for EVERY analyzer: `# tracelint: disable=...`
# silences TLxxx, SLxxx, RLxxx, NLxxx and KLxxx codes alike (shardlint/
# numlint/kernlint findings resolve back to a source line via the eqn's
# jax source_info; racelint findings are AST sites already).
# `# shardlint:` / `# racelint:` / `# numlint:` / `# kernlint:` are
# accepted aliases but scoped to their own family only — their `ALL`
# becomes the marker 'ALL:SL' / 'ALL:RL' / 'ALL:NL' / 'ALL:KL' and
# foreign codes are dropped, so a shardlint-spelled comment can never
# waive a trace-safety (TL) or kernel-interior (KL) finding and vice
# versa.  skip-file stays tracelint-spelled only, for the same reason.
_DISABLE_RE = re.compile(
    r"#\s*(tracelint|shardlint|racelint|numlint|kernlint|protolint):"
    r"\s*disable=([A-Za-z0-9,\s]+)")
_SKIP_FILE_RE = re.compile(r"^\s*#\s*tracelint:\s*skip-file\s*$")

_FAMILY = {"shardlint": "SL", "racelint": "RL", "numlint": "NL",
           "kernlint": "KL", "protolint": "PL"}


def parse_suppressions(source):
    """lineno -> set of suppressed codes ('ALL' suppresses everything;
    'ALL:SL'/'ALL:RL' suppresses one family). Returns (mapping,
    skip_file)."""
    sup = {}
    skip = False
    for i, raw in enumerate(source.splitlines(), start=1):
        if _SKIP_FILE_RE.match(raw):
            skip = True
        # finditer: a line may carry several spellings, and each merges
        for m in _DISABLE_RE.finditer(raw):
            codes = {c.strip().upper() for c in m.group(2).split(",")
                     if c.strip()}
            fam = _FAMILY.get(m.group(1))
            if fam is not None:
                codes = {f"ALL:{fam}" if c == "ALL" else c
                         for c in codes if c == "ALL"
                         or c.startswith(fam)}
            sup[i] = sup.get(i, set()) | codes
    return sup, skip


def _dotted(node):
    """Best-effort dotted name of an expression ('a.b.c' or '')."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_to_static_decorator(dec):
    """Matches @to_static, @paddle.jit.to_static, @jit.to_static, and the
    call forms to_static(...)."""
    if isinstance(dec, ast.Call):
        dec = dec.func
    name = _dotted(dec)
    return name.split(".")[-1] == "to_static"


@dataclass
class FunctionInfo:
    node: object                      # ast.FunctionDef
    qualname: str
    cls: object = None                # enclosing ast.ClassDef (methods)
    is_entry: bool = False


class ModuleIndex:
    """One parsed file: functions, classes, entry points, call graph."""

    def __init__(self, path, source, tree):
        self.path = path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.functions = []            # [FunctionInfo] in source order
        self.by_scope = {}             # id(scope node) -> {name: FunctionInfo}
        self.methods = {}              # id(ClassDef) -> {name: FunctionInfo}
        self.partial = False           # True when linting one explicit root
        self._index()

    def _index(self):
        def walk(body, scope_key, cls, prefix):
            local = self.by_scope.setdefault(scope_key, {})
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{prefix}{node.name}"
                    fi = FunctionInfo(node=node, qualname=qn, cls=cls)
                    fi.is_entry = any(is_to_static_decorator(d)
                                      for d in node.decorator_list)
                    if cls is not None and node.name == "forward":
                        fi.is_entry = True
                    self.functions.append(fi)
                    local[node.name] = fi
                    if cls is not None:
                        self.methods.setdefault(id(cls), {})[node.name] = fi
                    walk(node.body, id(node), None, qn + ".")
                elif isinstance(node, ast.ClassDef):
                    walk(node.body, id(node), node, f"{prefix}{node.name}.")
                elif isinstance(node, (ast.If, ast.Try, ast.With,
                                       ast.For, ast.While)):
                    # defs nested under plain statements stay in the same
                    # lexical scope for name resolution
                    walk(_stmt_children(node), scope_key, cls, prefix)
        walk(self.tree.body, id(self.tree), None, "")

    def entries(self):
        return [f for f in self.functions if f.is_entry]

    def reachable(self, roots=None):
        """Closure of module-local calls from `roots` (default: entries)."""
        roots = self.entries() if roots is None else roots
        seen, order = set(), []
        stack = list(roots)
        while stack:
            fi = stack.pop()
            if id(fi.node) in seen:
                continue
            seen.add(id(fi.node))
            order.append(fi)
            for callee in self._callees(fi):
                if id(callee.node) not in seen:
                    stack.append(callee)
        return order

    def _callees(self, fi):
        out = []
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                target = self._resolve_name(f.id, fi)
                if target is not None:
                    out.append(target)
            elif (isinstance(f, ast.Attribute)
                  and isinstance(f.value, ast.Name)
                  and f.value.id == "self" and fi.cls is not None):
                m = self.methods.get(id(fi.cls), {}).get(f.attr)
                if m is not None:
                    out.append(m)
        return out

    def _resolve_name(self, name, fi):
        # enclosing function scope first, then module scope
        for scope_key in (id(fi.node), id(self.tree)):
            hit = self.by_scope.get(scope_key, {}).get(name)
            if hit is not None and hit is not fi:
                return hit
        return None


def _stmt_children(node):
    out = []
    for fname in ("body", "orelse", "finalbody"):
        out.extend(getattr(node, fname, []) or [])
    for h in getattr(node, "handlers", []) or []:
        out.extend(h.body)
    return out


def walk_same_scope(node):
    """ast.walk that does not descend into nested function/class scopes
    (their bodies are linted via their own FunctionInfo when reached)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            stack.append(c)


# --------------------------------------------------------- tensor-likeness
# Attributes whose access on a tensor yields a NON-tensor (python) value.
NONTENSOR_ATTRS = {
    "shape", "dtype", "ndim", "name", "size", "numpy", "item", "tolist",
    "place", "stop_gradient",
}


class TensorEnv:
    """Heuristic intra-function tensor-likeness: parameters of an entry
    (minus `self`) are tensors; tensor-ness propagates through
    assignments, arithmetic, subscripts, method chains and calls that
    take a tensor argument.  Over-approximate on purpose — findings are
    hazards, and the baseline absorbs accepted ones."""

    def __init__(self, fdef, is_entry):
        self.names = set()
        if is_entry:
            a = fdef.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                if arg.arg != "self":
                    self.names.add(arg.arg)
        # forward pass over assignments, in source order
        for node in ast.walk(fdef):
            if isinstance(node, ast.Assign) and self.is_tensorish(node.value):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.names.add(n.id)
            elif isinstance(node, ast.AugAssign) and \
                    self.is_tensorish(node.value):
                if isinstance(node.target, ast.Name):
                    self.names.add(node.target.id)

    def is_tensorish(self, node):
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            if node.attr in NONTENSOR_ATTRS:
                return False
            return self.is_tensorish(node.value)
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr in NONTENSOR_ATTRS:
                    return False
                # h.mean(), self.conv(x), F.relu(x) ...
                if self.is_tensorish(f.value):
                    return True
            return any(self.is_tensorish(a) for a in node.args) or \
                any(self.is_tensorish(k.value) for k in node.keywords)
        if isinstance(node, ast.BinOp):
            return self.is_tensorish(node.left) or \
                self.is_tensorish(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tensorish(node.operand)
        if isinstance(node, ast.Compare):
            return self.is_tensorish(node.left) or \
                any(self.is_tensorish(c) for c in node.comparators)
        if isinstance(node, ast.Subscript):
            return self.is_tensorish(node.value)
        if isinstance(node, ast.IfExp):
            return self.is_tensorish(node.body) or \
                self.is_tensorish(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tensorish(e) for e in node.elts)
        return False


# ------------------------------------------------------------- file drive
def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def rel_path(path, base=None):
    base = base or os.getcwd()
    try:
        rel = os.path.relpath(os.path.abspath(path), base)
    except ValueError:
        rel = path
    if rel.startswith(".."):
        rel = path
    return rel.replace(os.sep, "/")


_parse_cache = {}  # (path, hash(source)) -> ast.Module


def _parse_cached(path, source):
    """Parse with a small memo: to_static(check=True) lints one module
    once per wrapped function — the parse (the dominant cost) is shared.
    The tree is never mutated by the lint, so sharing is safe."""
    key = (path, hash(source))
    tree = _parse_cache.get(key)
    if tree is None:
        tree = ast.parse(source)
        if len(_parse_cache) >= 64:
            _parse_cache.clear()
        _parse_cache[key] = tree
    return tree


def lint_source(path, source, rule_sets, base=None, select_roots=None):
    """Run `rule_sets` (callables: (index, reached) -> [Finding]) over one
    file's source. Returns suppression-filtered findings.
    `select_roots(index)` overrides the default entry set (used by the
    `to_static(check=True)` hook to lint one function's reach)."""
    try:
        tree = _parse_cached(path, source)
    except SyntaxError as e:
        return [Finding(path=rel_path(path, base), line=e.lineno or 1,
                        col=e.offset or 0, code="TL000",
                        message=f"syntax error: {e.msg}")]
    sup, skip = parse_suppressions(source)
    if skip:
        return []
    index = ModuleIndex(rel_path(path, base), source, tree)
    # partial: linting one explicit root (to_static(check=True)) rather
    # than the whole file — module-wide rules narrow their scope then
    index.partial = select_roots is not None
    roots = select_roots(index) if select_roots is not None else None
    reached = index.reachable(roots)
    findings = []
    for rs in rule_sets:
        findings.extend(rs(index, reached))
    out = []
    for f in findings:
        codes = sup.get(f.line, ())
        if "ALL" in codes or f.code in codes:
            continue
        if 1 <= f.line <= len(index.lines):
            f.source_line = index.lines[f.line - 1].strip()
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out
