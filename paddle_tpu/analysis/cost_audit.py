"""shardlint SL3xx: memory & layout cost audit of a traced jaxpr.

Three estimates the TPU will otherwise only reveal at runtime:

- **peak HBM** — a linear-scan liveness walk over the program: inputs
  and consts are resident, each eqn allocates its outputs and frees
  operands past their last use; the maximum resident set is the
  estimate, and the arrays live at that moment are the "top
  contributors".  Sub-jaxprs (scan/while/cond bodies) contribute their
  own internal peak beyond the operands already counted.  This is an
  ESTIMATE — XLA fuses, rematerializes and buffer-shares — but it
  ranks programs and catches order-of-magnitude blowups before any
  compile (SL301 when a budget is declared).
- **MXU padding waste** — every dot/conv operand is padded to the TPU
  tile: (sublane x 128-lane) blocks, 8x128 for f32, 16x128 for bf16,
  32x128 for int8.  A dim just past a tile boundary pays for the whole
  next tile; SL302 flags operands whose padded footprint wastes more
  than the threshold, and the program-wide waste fraction feeds the
  bench report lane.
- **f32-storage / bf16-compute** — an input whose only first touch is a
  convert_element_type f32->bf16 could be stored half-size (SL303).

Module-level imports are stdlib-only; jax types arrive via the jaxpr.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from paddle_tpu.analysis.jaxpr_rules import _iter_eqns, _sub_jaxprs
from paddle_tpu.analysis.shard_rules import (AuditConfig, _aval_sig,
                                             _fmt_bytes, _mk_finding,
                                             _nbytes_of)

__all__ = ["CostReport", "audit_memory", "tile_padded_elems"]

_MIB = 1 << 20

# primitives that execute on the MXU (systolic array) and therefore pay
# tile padding on their operands
MXU_PRIMS = ("dot_general", "conv_general_dilated")


@dataclass
class CostReport:
    """Per-program cost summary (bench lane + CLI report schema)."""

    where: str
    n_eqns: int = 0
    peak_hbm_bytes: int = 0
    top: list = field(default_factory=list)   # [(bytes, label)]
    mxu_bytes: int = 0
    mxu_padded_bytes: int = 0
    n_mxu_ops: int = 0

    @property
    def padding_waste(self):
        """Fraction of MXU operand tile footprint that is padding."""
        if not self.mxu_padded_bytes:
            return 0.0
        return 1.0 - self.mxu_bytes / self.mxu_padded_bytes

    def to_dict(self):
        return {
            "where": self.where,
            "n_eqns": self.n_eqns,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "peak_hbm_mb": round(self.peak_hbm_bytes / _MIB, 3),
            "padding_waste_pct": round(100.0 * self.padding_waste, 2),
            "n_mxu_ops": self.n_mxu_ops,
            "top_contributors": [
                {"bytes": b, "label": lbl} for b, lbl in self.top],
        }


def tile_padded_elems(shape, itemsize):
    """Element count of `shape` once padded to the MXU tile for the
    dtype: last dim -> multiple of 128 lanes, second-minor -> multiple
    of the sublane count (32 // itemsize, min 8)."""
    if not shape:
        return 1
    dims = [max(1, int(d)) for d in shape]
    sublane = max(8, 32 // max(1, int(itemsize)))
    dims[-1] = -(-dims[-1] // 128) * 128
    if len(dims) >= 2:
        dims[-2] = -(-dims[-2] // sublane) * sublane
    n = 1
    for d in dims:
        n *= d
    return n


def _peak_scan(jaxpr, input_bytes, labels, top_n):
    """Liveness walk of one (open) jaxpr.

    `input_bytes`: {var: nbytes} for values resident at entry (invars,
    constvars).  Returns (peak_bytes, [(bytes, label)] at the peak)."""
    last_use = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not hasattr(v, "val"):
                last_use[v] = i
    for v in jaxpr.outvars:
        if not hasattr(v, "val"):
            last_use[v] = len(jaxpr.eqns)

    live = dict(input_bytes)
    current = sum(live.values())
    peak, snapshot = current, sorted(
        ((b, labels.get(v, "input")) for v, b in live.items()),
        reverse=True)[:top_n]

    for i, eqn in enumerate(jaxpr.eqns):
        for ov in eqn.outvars:
            b = _nbytes_of(ov)
            live[ov] = b
            labels[ov] = f"{eqn.primitive.name} {_aval_sig(ov)}"
            current += b
        inner_extra = 0
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                inner = getattr(sub, "jaxpr", sub)
                consts = sum(_nbytes_of(c)
                             for c in getattr(sub, "consts", []) or [])
                sub_inputs = {iv: _nbytes_of(iv) for iv in inner.invars}
                sub_labels = {iv: f"{eqn.primitive.name}-body input "
                                  f"{_aval_sig(iv)}" for iv in inner.invars}
                sub_peak, _ = _peak_scan(inner, sub_inputs, sub_labels,
                                         top_n)
                # the body's inputs alias operands already counted live;
                # only the EXTRA allocation inside the body stacks on top
                inner_extra += max(
                    0, sub_peak - sum(sub_inputs.values())) + consts
        candidate = current + inner_extra
        if candidate > peak:
            peak = candidate
            snapshot = sorted(((b, labels.get(v, "?"))
                               for v, b in live.items()),
                              reverse=True)[:top_n]
        for v in list(eqn.invars) + list(eqn.outvars):
            if hasattr(v, "val"):
                continue
            if last_use.get(v, i) <= i and v in live:
                current -= live.pop(v)
    return peak, snapshot


def audit_memory(closed_jaxpr, where="<traced program>", inputs=None,
                 config=None):
    """Run the SL3xx pass; returns ([Finding], CostReport)."""
    config = config or AuditConfig()
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    findings = []

    # ---- peak HBM (liveness estimate) ----
    input_bytes, labels = {}, {}
    names = list(inputs or ())
    for i, iv in enumerate(jaxpr.invars):
        input_bytes[iv] = _nbytes_of(iv)
        nm = names[i].name if i < len(names) else f"arg{i}"
        labels[iv] = f"input `{nm}` {_aval_sig(iv)}"
    const_bytes = 0
    for cv, c in zip(jaxpr.constvars,
                     getattr(closed_jaxpr, "consts", []) or []):
        b = int(getattr(c, "nbytes", 0) or 0)
        input_bytes[cv] = b
        labels[cv] = f"const {_aval_sig(cv)}"
        const_bytes += b
    peak, top = _peak_scan(jaxpr, input_bytes, labels,
                           config.top_contributors)

    rep = CostReport(where=where,
                     n_eqns=sum(1 for _ in _iter_eqns(closed_jaxpr)),
                     peak_hbm_bytes=peak, top=top)

    if config.hbm_budget_bytes and peak > config.hbm_budget_bytes:
        heads = "; ".join(f"{lbl}={_fmt_bytes(b)}" for b, lbl in top[:3])
        findings.append(_mk_finding(
            "SL301",
            f"{_fmt_bytes(peak)} > budget "
            f"{_fmt_bytes(config.hbm_budget_bytes)} (top: {heads})",
            where, sig=f"peak {where}"))

    # ---- MXU tile padding (SL302) ----
    seen = set()
    for eqn in _iter_eqns(closed_jaxpr):
        if eqn.primitive.name not in MXU_PRIMS:
            continue
        for opv in eqn.invars[:2]:
            aval = getattr(opv, "aval", None)
            dt = getattr(aval, "dtype", None)
            shape = tuple(getattr(aval, "shape", ()) or ())
            if dt is None or not shape:
                continue
            itemsize = int(getattr(dt, "itemsize", 4) or 4)
            size = 1
            for d in shape:
                size *= int(d)
            padded = tile_padded_elems(shape, itemsize)
            rep.mxu_bytes += size * itemsize
            rep.mxu_padded_bytes += padded * itemsize
            rep.n_mxu_ops += 1
            waste = 1.0 - size / padded if padded else 0.0
            key = (eqn.primitive.name, shape, str(dt))
            if waste >= config.padding_waste_threshold and \
                    size * itemsize >= config.mxu_min_bytes and \
                    key not in seen:
                seen.add(key)
                sub = max(8, 32 // itemsize)
                findings.append(_mk_finding(
                    "SL302",
                    f"{_aval_sig(opv)} of `{eqn.primitive.name}` pads to "
                    f"({sub},128) tiles: {waste * 100:.1f}% waste "
                    f"({_fmt_bytes(padded * itemsize - size * itemsize)})",
                    where, eqn=eqn,
                    sig=f"pad {eqn.primitive.name} {_aval_sig(opv)}"))

    # ---- f32 storage for bf16 compute (SL303) ----
    # flag an f32 input ONLY when every top-level consumer is a
    # convert_element_type to bf16 — a param also read in f32 (optimizer
    # master-weight math, f32 layernorm) legitimately stays f32
    program_inputs = {iv: i for i, iv in enumerate(jaxpr.invars)}
    consumers = {}
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if not hasattr(v, "val") and v in program_inputs:
                consumers.setdefault(v, []).append(eqn)
    for v, eqns in consumers.items():
        aval = getattr(v, "aval", None)
        if str(getattr(aval, "dtype", "")) != "float32":
            continue
        if _nbytes_of(v) < config.f32_param_min_bytes:
            continue
        casts = [e for e in eqns
                 if e.primitive.name == "convert_element_type"
                 and str(e.params.get("new_dtype", "")) == "bfloat16"]
        if casts and len(casts) == len(eqns):
            nm_i = program_inputs[v]
            nm = names[nm_i].name if nm_i < len(names) else f"arg{nm_i}"
            findings.append(_mk_finding(
                "SL303",
                f"`{nm}` {_aval_sig(v)} ({_fmt_bytes(_nbytes_of(v))}; "
                f"bf16 storage would halve it)",
                where, eqn=casts[0], sig=f"f32->bf16 {nm}"))
    return findings, rep
