"""shardlint SL1xx/SL2xx: sharding & collective-safety audit of jaxprs.

tracelint's TL4xx pass stops at "does this collective have a mesh";
shardlint goes the rest of the way: an abstract-interpretation walk over
the traced program that knows shapes, dtypes and shardings per eqn and
asks the questions that decide whether the program SCALES —

- SL1xx sharding: large arrays left fully replicated on a multi-device
  mesh (SL101), optimizer state unsharded under data parallelism
  (SL102), and A->B->A resharding-constraint thrash (SL103);
- SL2xx collective safety: cond branches whose collective sequences
  diverge and can deadlock SPMD shards (SL201), all_gathers that
  materialize past the per-chip HBM budget (SL202), and loop-invariant
  collectives trapped inside scan bodies (SL203).

The SL3xx memory/layout pass lives in :mod:`cost_audit`; the shared
driver is :func:`paddle_tpu.analysis.audit_jaxpr`.

Sharding facts come from two places: the `dist_spec` annotations on the
lifted state tensors (:func:`input_infos_from_state` — available even
when tracing on a single CPU device, which is the whole point of a
STATIC auditor) and `sharding_constraint` eqns when the program was
traced under a real mesh.  The mesh itself can be hypothetical: pass
``MeshInfo.of(axes={"dp": 8, "tp": 4})`` to audit a CPU-traced program
against the production topology before any TPU time is spent.

Findings resolve back to a source line through each eqn's jax
source_info, so the ordinary ``# tracelint: disable=SL201`` per-line
suppressions apply (see :func:`apply_suppressions`).

Module-level imports are stdlib-only (jax loads lazily inside the
checks) so `tools/` CLIs can import the package light.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

from paddle_tpu.analysis.jaxpr_rules import (COLLECTIVE_PRIMS, _axis_names,
                                             _iter_eqns, _sub_jaxprs)
from paddle_tpu.analysis.rules import message_for
from paddle_tpu.analysis.visitor import Finding, parse_suppressions, rel_path

__all__ = [
    "AuditConfig", "MeshInfo", "InputInfo", "input_infos_from_state",
    "check_sharding", "check_collectives", "apply_suppressions",
]

_MIB = 1 << 20

# finding paths (and therefore baseline fingerprints) anchor to the REPO
# root, not the CWD — `shardlint --check` must agree with the checked-in
# baseline no matter where it is invoked from
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@dataclass(frozen=True)
class AuditConfig:
    """Thresholds for the SL rule families (one knob set shared by the
    CLI, the to_static(audit=True) hook, and the serving self-audit)."""

    # SL101: smallest replicated array worth flagging
    large_replicated_bytes: int = 16 * _MIB
    # SL102: smallest optimizer-state tensor worth flagging
    opt_state_min_bytes: int = 64 << 10
    # SL202: per-chip budget an all_gather result may not exceed
    allgather_budget_bytes: int = 1 << 30
    # SL301: peak-HBM budget (None = report the estimate, never flag)
    hbm_budget_bytes: int = None
    # SL302: minimum waste fraction + operand size to flag
    padding_waste_threshold: float = 0.15
    mxu_min_bytes: int = 16 << 10
    # SL303: smallest f32 input worth flagging
    f32_param_min_bytes: int = 64 << 10
    # cost report: how many peak contributors to name
    top_contributors: int = 5


@dataclass(frozen=True)
class MeshInfo:
    """The (possibly hypothetical) device mesh an audit runs against."""

    axis_sizes: tuple  # ((axis_name, size), ...)

    @classmethod
    def of(cls, mesh=None, axes=None):
        """From an explicit ``axes`` dict, a jax Mesh (or anything with
        ``axis_names`` + a ``shape`` mapping), or the installed global
        mesh.  Returns None when no mesh is known anywhere."""
        if axes:
            return cls(tuple((str(a), int(n)) for a, n in axes.items()))
        if mesh is None:
            from paddle_tpu.distributed import mesh as dmesh
            mesh = dmesh.get_mesh()
        if mesh is None:
            return None
        shape = dict(getattr(mesh, "shape", None) or {})
        names = tuple(getattr(mesh, "axis_names", ()) or ())
        return cls(tuple((str(a), int(shape.get(a, 1))) for a in names))

    @property
    def axis_names(self):
        return tuple(a for a, _ in self.axis_sizes)

    def size(self, name, default=1):
        return dict(self.axis_sizes).get(name, default)

    @property
    def n_devices(self):
        n = 1
        for _, s in self.axis_sizes:
            n *= s
        return n

    def describe(self):
        return "x".join(f"{a}={s}" for a, s in self.axis_sizes) or "<empty>"


# Accumulator names from optimizer/: a state tensor named
# `{param}_{acc_name}` (see Optimizer._acc) is optimizer state.  Exact
# SUFFIX match against the known accumulator names — a substring match
# would misclassify a param that merely contains "moment" in its name.
OPT_STATE_SUFFIXES = tuple(
    "_" + n for n in (
        "moment", "moment1", "moment2", "momentum", "velocity",
        "inf_norm", "mean_square", "mean_grad", "avg_squared_grad",
        "avg_squared_update", "acc_grad", "gm_acc", "gm_count", "master",
        "beta1_pow", "beta2_pow", "sum_1", "sum_2", "sum_3", "dfl_step",
    ))


@dataclass
class InputInfo:
    """What the auditor knows about one program input (jaxpr invar)."""

    name: str
    kind: str = "input"      # param | opt_state | input | other
    spec: tuple = None       # PartitionSpec entries (None = replicated)
    shape: tuple = ()
    dtype: str = ""
    nbytes: int = 0

    def sharded_over(self, mesh):
        """Mesh axes (size > 1) this input is actually partitioned on."""
        if not self.spec or mesh is None:
            return ()
        axes = []
        for entry in self.spec:
            entry = entry if isinstance(entry, (list, tuple)) else (entry,)
            axes.extend(a for a in entry
                        if isinstance(a, str) and mesh.size(a) > 1)
        return tuple(axes)


def input_infos_from_state(state_tensors):
    """InputInfos for to_static's lifted state list, in lift order.

    kind comes from paddle_tpu naming: optimizer accumulators are named
    `{param}_{marker}` (OPT_STATE_MARKERS); everything else persistable
    counts as a parameter/buffer.  Sharding comes from the `dist_spec`
    annotation (mesh-independent, set by shard_tensor)."""
    from paddle_tpu.distributed.mesh import get_dist_spec
    infos = []
    for t in state_tensors:
        name = getattr(t, "name", "") or ""
        kind = "opt_state" if name.endswith(OPT_STATE_SUFFIXES) else "param"
        spec = get_dist_spec(t)
        v = getattr(t, "_value", None)
        shape = tuple(getattr(v, "shape", ()) or ())
        dtype = str(getattr(v, "dtype", ""))
        nbytes = int(getattr(v, "nbytes", 0) or 0)
        infos.append(InputInfo(name=name, kind=kind,
                               spec=tuple(spec) if spec is not None else None,
                               shape=shape, dtype=dtype, nbytes=nbytes))
    return infos


# ----------------------------------------------------------- finding plumbing
def _eqn_site(eqn):
    """(abs_path, line) of the first USER frame that emitted this eqn,
    or (None, 0) — jax's source_info survives tracing, so a jaxpr
    finding can point at real code (and per-line suppressions apply)."""
    try:
        from jax._src import source_info_util as siu
        fr = siu.user_frame(eqn.source_info)
        if fr is not None and fr.file_name and os.path.exists(fr.file_name):
            return fr.file_name, int(fr.start_line or 0)
    except Exception:
        pass
    return None, 0


def _mk_finding(code, detail, where, eqn=None, sig=""):
    path, line = _eqn_site(eqn) if eqn is not None else (None, 0)
    return Finding(
        path=rel_path(path, base=_REPO_ROOT) if path else where,
        line=line, col=0,
        code=code, message=message_for(code, detail=detail),
        # for non-file findings the stable signature doubles as the
        # baseline fingerprint text (report.fingerprint hashes it)
        source_line=sig)


def _fmt_bytes(n):
    if n >= _MIB:
        return f"{n / _MIB:.1f} MiB"
    return f"{n / 1024:.1f} KiB"


def _aval_sig(v):
    aval = getattr(v, "aval", None)
    dt = getattr(aval, "dtype", None)
    shape = tuple(getattr(aval, "shape", ()) or ())
    return f"{getattr(dt, 'name', dt)}{list(shape)}"


# ----------------------------------------------------------------- SL1xx
def check_sharding(closed_jaxpr, inputs=None, mesh=None, config=None,
                   where="<traced program>"):
    """SL101/SL102 over the program inputs + SL103 over constraint eqns."""
    config = config or AuditConfig()
    mesh = mesh if isinstance(mesh, MeshInfo) else MeshInfo.of(mesh)
    findings = []

    if mesh is not None and mesh.n_devices > 1:
        for info in inputs or ():
            if info.sharded_over(mesh):
                continue
            if info.kind == "opt_state" and \
                    info.nbytes >= config.opt_state_min_bytes:
                findings.append(_mk_finding(
                    "SL102",
                    f"`{info.name}` ({_fmt_bytes(info.nbytes)}, "
                    f"{info.dtype}{list(info.shape)}) on mesh "
                    f"{mesh.describe()}",
                    where, sig=f"opt_state {info.name}"))
            elif info.kind == "param" and \
                    info.nbytes >= config.large_replicated_bytes:
                findings.append(_mk_finding(
                    "SL101",
                    f"`{info.name}` ({_fmt_bytes(info.nbytes)}, "
                    f"{info.dtype}{list(info.shape)}) on mesh "
                    f"{mesh.describe()}",
                    where, sig=f"param {info.name}"))

    _thrash_walk(getattr(closed_jaxpr, "jaxpr", closed_jaxpr),
                 findings, where)
    return findings


def _norm_spec(sharding):
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    out = []
    for e in tuple(spec):
        out.append(tuple(e) if isinstance(e, (list, tuple)) else e)
    return tuple(out)


def _thrash_walk(jaxpr, findings, where):
    """SL103: follow sharding_constraint chains through dataflow and
    flag A->B->A bounces (one finding per bounce site)."""
    hist = {}  # var -> tuple of constraint specs on its lineage
    for eqn in jaxpr.eqns:
        inherited = ()
        for v in eqn.invars:
            if hasattr(v, "val"):     # Literal
                continue
            h = hist.get(v)
            if h:
                inherited = h
                break
        if eqn.primitive.name == "sharding_constraint":
            spec = _norm_spec(eqn.params.get("sharding"))
            if spec is not None:
                if inherited and inherited[-1] != spec and spec in inherited:
                    findings.append(_mk_finding(
                        "SL103",
                        f"{inherited[-1]} -> {spec} "
                        f"(earlier already {spec})",
                        where, eqn=eqn,
                        sig=f"thrash {inherited[-1]}->{spec}"))
                inherited = inherited + (spec,)
        if inherited:
            for ov in eqn.outvars:
                hist[ov] = inherited
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                _thrash_walk(getattr(sub, "jaxpr", sub), findings, where)


# ----------------------------------------------------------------- SL2xx
def check_collectives(closed_jaxpr, mesh=None, config=None,
                      where="<traced program>"):
    """SL201 (branch-divergent collectives), SL202 (all_gather size),
    SL203 (loop-invariant collectives in scan bodies)."""
    config = config or AuditConfig()
    findings = []
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)

    # SL202: the gathered aval already has the post-gather shape
    seen = set()
    for eqn in _iter_eqns(closed_jaxpr):
        if eqn.primitive.name not in ("all_gather", "all_to_all"):
            continue
        out = eqn.outvars[0]
        nbytes = _nbytes_of(out)
        key = (eqn.primitive.name, _aval_sig(out))
        if nbytes >= config.allgather_budget_bytes and key not in seen:
            seen.add(key)
            findings.append(_mk_finding(
                "SL202",
                f"{_aval_sig(out)} = {_fmt_bytes(nbytes)} per chip "
                f"(budget {_fmt_bytes(config.allgather_budget_bytes)})",
                where, eqn=eqn, sig=f"all_gather {_aval_sig(out)}"))

    _branch_walk(jaxpr, findings, where)
    _scan_walk(jaxpr, findings, where)
    return findings


# COLLECTIVE_PRIMS entries that perform NO cross-chip communication
# (axis_index reads the local coordinate; pbroadcast is a type-level
# rebinding): they cannot deadlock and cost nothing per scan iteration,
# so SL201/SL203 must not treat them as rendezvous points.
NON_RENDEZVOUS_PRIMS = ("axis_index", "pbroadcast")


def _rendezvous_axes(eqn):
    if eqn.primitive.name in NON_RENDEZVOUS_PRIMS:
        return None
    return _axis_names(eqn)


def _collective_signature(jaxpr_like):
    """STRUCTURED (primitive, axes) sequence of the collectives a
    (sub)jaxpr issues — the rendezvous schedule SPMD shards must agree
    on.  Control flow is kept structural rather than flattened: a
    nested cond whose branches all agree contributes that common
    schedule once (every runtime path issues it exactly once); a
    divergent nested cond becomes an opaque token (it gets its own
    SL201 from the recursive walk); a loop wraps its body's schedule in
    a (loop, ...) token, since its collectives repeat per iteration and
    must not compare equal to a single straight-line issue."""
    sig = []
    jx = getattr(jaxpr_like, "jaxpr", jaxpr_like)
    for eqn in jx.eqns:
        prim = eqn.primitive.name
        if prim == "cond":
            subs = [_collective_signature(b)
                    for b in eqn.params.get("branches", ())]
            if subs and all(s == subs[0] for s in subs):
                sig.extend(subs[0])
            elif any(subs):
                sig.append(("cond!", tuple(subs)))
        elif prim in ("scan", "while"):
            inner = []
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    inner.extend(_collective_signature(sub))
            if inner:
                sig.append((prim, tuple(inner)))
        else:
            names = _rendezvous_axes(eqn)
            if names:
                sig.append((prim, names))
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    sig.extend(_collective_signature(sub))
    return tuple(sig)


def _fmt_sig(sig):
    return "[" + ", ".join(
        f"{p}@{list(a)}" if a and isinstance(a[0], str) else f"{p}{{...}}"
        for p, a in sig) + "]"


def _branch_walk(jaxpr, findings, where):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "cond":
            branches = eqn.params.get("branches", ())
            sigs = [_collective_signature(b) for b in branches]
            if len(set(sigs)) > 1:
                desc = " vs ".join(_fmt_sig(s) for s in sigs)
                findings.append(_mk_finding(
                    "SL201", desc, where, eqn=eqn,
                    sig=f"cond {desc}"))
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                _branch_walk(getattr(sub, "jaxpr", sub), findings, where)


def _scan_walk(jaxpr, findings, where):
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "scan":
            body = eqn.params.get("jaxpr")
            if body is not None:
                _flag_invariant_collectives(
                    getattr(body, "jaxpr", body),
                    int(eqn.params.get("num_consts", 0)),
                    findings, where, loop=prim)
        elif prim == "while":
            body = eqn.params.get("body_jaxpr")
            if body is not None:
                bj = getattr(body, "jaxpr", body)
                n = int(eqn.params.get("body_nconsts", 0))
                # while puts EVERYTHING in the carry (no consts/xs
                # split like scan), so a purely const-based invariance
                # pass sees nothing: also treat fixed-point carry slots
                # — written back unchanged every iteration — as
                # invariant
                fixed = {iv for iv, ov in zip(bj.invars[n:], bj.outvars)
                         if ov is iv}
                _flag_invariant_collectives(
                    bj, n, findings, where, loop=prim,
                    invariant_carry=fixed)
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                _scan_walk(getattr(sub, "jaxpr", sub), findings, where)


def _flag_invariant_collectives(body, num_consts, findings, where,
                                loop="scan", invariant_carry=()):
    """SL203: inside one loop body, a collective whose operands depend
    only on the body's consts (loop-invariant) re-runs every iteration
    for the same answer.  Sub-jaxprs fed ONLY invariant operands are
    entirely invariant, so a collective anywhere inside them flags too;
    sub-jaxprs touching variant operands are skipped conservatively
    (inner loops get their own pass from _scan_walk).
    `invariant_carry`: carry invars proven invariant by the caller
    (while fixed-point slots)."""
    variant = set(body.invars[num_consts:])   # carry + xs change per iter
    variant -= set(invariant_carry)
    for eqn in body.eqns:
        ins_variant = any(v in variant for v in eqn.invars
                          if not hasattr(v, "val"))
        subs = [s for v in eqn.params.values() for s in _sub_jaxprs(v)]

        def _flag(e, names):
            findings.append(_mk_finding(
                "SL203",
                f"{e.primitive.name}(axis={list(names)})",
                where, eqn=e,
                sig=f"{loop} {e.primitive.name}@{list(names)}"))

        if not ins_variant:
            names = _rendezvous_axes(eqn)
            if names:
                _flag(eqn, names)
            # nested loops are excluded: _scan_walk gives their bodies
            # their own invariance pass (flagging here would duplicate)
            if eqn.primitive.name not in ("scan", "while"):
                for sub in subs:
                    for inner in _iter_eqns(sub):
                        inner_names = _rendezvous_axes(inner)
                        if inner_names:
                            _flag(inner, inner_names)
        else:
            variant.update(eqn.outvars)


def _nbytes_of(v):
    aval = getattr(v, "aval", None)
    size = getattr(aval, "size", None)
    dt = getattr(aval, "dtype", None)
    if size is None or dt is None:
        return 0
    return int(size) * int(getattr(dt, "itemsize", 0) or 0)


# ----------------------------------------------------------- suppressions
_src_cache = {}


def _file_suppressions(path):
    """(lineno -> codes, skip_file) for `path`, cached per file."""
    hit = _src_cache.get(path)
    if hit is not None:
        return hit
    try:
        with open(path, encoding="utf-8", errors="replace") as fh:
            source = fh.read()
    except OSError:
        source = ""
    sup, skip = parse_suppressions(source)
    if len(_src_cache) > 256:
        _src_cache.clear()
    _src_cache[path] = (sup, skip)
    return _src_cache[path]


def apply_suppressions(findings):
    """Drop findings whose resolved source line carries a
    `# tracelint: disable=<code>` (or a family-scoped alias —
    `# shardlint:` for SL codes, `# numlint:` for NL codes) comment,
    exactly like the AST pass.  The family-wide marker (`ALL:SL` /
    `ALL:NL`, produced by an alias-spelled `disable=ALL`) only waives
    findings of ITS family, keyed on the code prefix.  Findings without
    a real file site pass through untouched — their baseline
    fingerprints hash the stable `sig` every _mk_finding sets as
    source_line."""
    out = []
    for f in findings:
        path = None
        for cand in (f.path, os.path.join(_REPO_ROOT, f.path)):
            if os.path.exists(cand):
                path = cand
                break
        if path is None or f.line <= 0:
            out.append(f)
            continue
        sup, skip = _file_suppressions(path)
        if skip:
            continue
        codes = sup.get(f.line, ())
        if "ALL" in codes or f"ALL:{f.code[:2]}" in codes \
                or f.code in codes:
            continue
        out.append(f)
    return out
