"""numlint dataflow: per-value dtype provenance over a traced jaxpr.

The whole-program trace makes every dtype decision in a training or
serving step visible in ONE jaxpr — so precision invariants that the
repo otherwise holds only by convention (master weights stay f32,
reductions accumulate wide, stabilized interiors are not re-narrowed,
quantized codes travel with their scales) can be PROVEN statically,
before any silicon time.  This module is the provenance layer: it walks
the program once and records, for every value,

- the **cast lineage** — which wide dtype it was narrowed from, at
  which eqn, and whether the wide original still has live consumers
  (the double-rounding question);
- the **stabilization state** — whether a max-subtraction or an
  eps-guard sits upstream of it (the softmax/log/div overflow
  question);
- the **quantization lineage** — whether it is a raw int8/fp8 code, a
  dequantized float derived from one, and whether a scale multiply has
  been applied (the ROADMAP-item-2 KV-quantization questions).

It also records the EVENTS the NL rule catalog judges: narrow
reductions (NL101), narrow→wide round trips (NL102), narrow
transcendentals (NL201), narrow scan carries with wide body math
(NL202), and quantized-value consumptions / dequant→requant chains
(NL301/NL302).  The judging itself — thresholds, allowlists, finding
construction — lives in :mod:`num_rules`; this module only states
facts about the program.

Sub-jaxprs are walked with their operand provenance mapped through
(pjit bodies, scan/while carries, cond branches, custom-vjp calls), so
lineage survives jax's call-boundary plumbing.  ``pallas_call`` bodies
are deliberately OPAQUE: a kernel's refs are not values, and the house
kernels (ops/pallas/) pin their f32-stabilized interiors with their own
tests — their call-boundary outputs enter the flow as fresh values.

Module-level imports are stdlib-only (the jaxpr carries every jax type
we touch) so the CLI can import the package light.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace

from paddle_tpu.analysis.jaxpr_rules import _sub_jaxprs

__all__ = ["DtypeFlow", "Prov", "NARROW_FLOATS", "WIDE_FLOATS",
           "QUANT_DTYPES"]

NARROW_FLOATS = ("bfloat16", "float16")
WIDE_FLOATS = ("float32", "float64")
# int8/uint8 double as mask/index carriers — quant lineage for them
# starts only at a convert-to-float or float-math consumption; the fp8
# family is unambiguous.
QUANT_DTYPES = ("int8", "uint8", "float8_e4m3fn", "float8_e5m2",
                "float8_e4m3", "float8_e4m3fnuz", "float8_e5m2fnuz",
                "float8_e4m3b11fnuz")

# reductions that serially accumulate in their OUTPUT dtype when
# lowered (unlike the MXU's in-hardware wide dot accumulation, these
# are exactly as narrow as they say)
SERIAL_REDUCE_PRIMS = ("reduce_sum", "cumsum", "reduce_window_sum")

# transcendentals whose narrow-dtype evaluation saturates/amplifies
# without upstream stabilization (div is special-cased: only its
# DENOMINATOR is judged, and literal/const denominators are safe)
TRANSCENDENTAL_PRIMS = ("exp", "exp2", "expm1", "log", "log1p", "div",
                        "rsqrt")

_ELEMENTWISE_LINEAGE = frozenset((
    "add", "sub", "mul", "neg", "max", "min", "select_n", "abs",
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "gather", "rev", "expand_dims", "copy", "stop_gradient",
    "reduce_sum", "reduce_max", "reduce_min", "cumsum", "add_any",
))


@dataclass
class Prov:
    """What the flow knows about one value (jaxpr var)."""

    dtype: str
    origin: str = ""               # dtype at creation / program entry
    # cast lineage
    narrowed_from: str = None      # wide dtype lost on the path here
    narrow_eqn: object = None      # the convert eqn that narrowed
    wide_root: object = None       # the var holding the pre-narrow value
    wide_root_is_input: bool = False
    wide_live_hint: bool = False   # root proven live when the narrow
    # value crossed a call boundary (the root var itself is only
    # meaningful at its own level; the hint carries its liveness in)
    # stability
    stabilized: bool = False       # max-subtraction / eps-guard upstream
    from_max: bool = False         # derives from a reduce_max (softmax)
    # quantization lineage
    quant: bool = False            # raw int8/fp8 codes
    dequant_of: object = None      # quant var this float was converted from
    descaled: bool = False         # a scale multiply has been applied
    bcast_src_size: int = None     # pre-broadcast element count — a
    # per-page/per-block scale is tiny until jnp broadcasting expands it
    # to the code shape right before the mul; the source size is what
    # the scale-shape judgments below must see

    def clone(self, **kw):
        return replace(self, **kw)


@dataclass
class ReduceEvent:
    eqn: object
    prim: str
    operand_prov: Prov
    reduce_elems: int              # addends per output element
    out_dtype: str
    widened: bool                  # accumulation/output is wide


@dataclass
class RoundTripEvent:
    widen_eqn: object
    narrow_eqn: object
    wide_dtype: str
    narrow_dtype: str
    wide_root: object
    wide_root_is_input: bool
    wide_live: bool                # wide root has other live consumers


@dataclass
class TranscendentalEvent:
    eqn: object
    prim: str
    operand_prov: Prov             # the judged operand (denominator for div)
    stabilized: bool


@dataclass
class ScanCarryEvent:
    eqn: object
    slot: int
    carry_dtype: str
    body_dtype: str                # the wide dtype the body computes in


@dataclass
class QuantUseEvent:
    eqn: object
    prim: str
    operand: object
    operand_dtype: str
    raw: bool                      # raw codes (True) vs un-descaled dequant
    has_scale_operand: bool        # a scale-shaped float rides along


@dataclass
class RequantEvent:
    eqn: object                    # the re-quantizing convert
    dequant_eqn: object
    intermediate_other_uses: int   # consumers of the float besides requant


@dataclass
class FlowResult:
    reductions: list = field(default_factory=list)
    round_trips: list = field(default_factory=list)
    transcendentals: list = field(default_factory=list)
    scan_carries: list = field(default_factory=list)
    quant_uses: list = field(default_factory=list)
    requants: list = field(default_factory=list)


def _dtype_of(v):
    return str(getattr(getattr(v, "aval", None), "dtype", ""))


def _is_literal(v):
    return hasattr(v, "val")


def _size_of(v):
    aval = getattr(v, "aval", None)
    n = 1
    for d in getattr(aval, "shape", ()) or ():
        n *= int(d)
    return n


def _eps_literal(v, eps_max):
    """A small positive literal/scalar (an eps-guard candidate)."""
    if not _is_literal(v):
        return False
    try:
        val = float(v.val)
    except (TypeError, ValueError):
        return False
    return 0.0 < val <= eps_max


class DtypeFlow:
    """One pass over a (Closed)Jaxpr; facts land on :attr:`result`.

    `inputs`: optional [InputInfo] aligned with the top-level invars
    (names/kinds flow into provenance so NL103 can tell a param from an
    activation).  `eps_max`: largest additive literal that counts as an
    eps-guard for stabilization tracking.
    """

    def __init__(self, closed_jaxpr, inputs=None, eps_max=1e-2):
        self.result = FlowResult()
        self.eps_max = eps_max
        self.input_infos = {}
        jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
        provs = {}
        for i, iv in enumerate(jaxpr.invars):
            dt = _dtype_of(iv)
            provs[iv] = Prov(dtype=dt, origin=dt,
                             quant=dt in QUANT_DTYPES)
            if inputs is not None and i < len(inputs):
                self.input_infos[iv] = inputs[i]
        for cv, c in zip(jaxpr.constvars,
                         getattr(closed_jaxpr, "consts", []) or []):
            dt = str(getattr(c, "dtype", "")) or _dtype_of(cv)
            provs[cv] = Prov(dtype=dt, origin=dt,
                             quant=dt in QUANT_DTYPES)
        self._walk(jaxpr, provs, top=True)

    # ------------------------------------------------------------ core walk
    def _prov(self, env, v):
        if _is_literal(v):
            dt = str(getattr(v.val, "dtype", type(v.val).__name__))
            return Prov(dtype=dt, origin=dt)
        p = env.get(v)
        if p is None:
            dt = _dtype_of(v)
            p = Prov(dtype=dt, origin=dt, quant=dt in QUANT_DTYPES)
            env[v] = p
        return p

    def _walk(self, jaxpr, env, top=False):
        # liveness for the double-rounding question: a wide root is
        # "still live" at a re-widen if it has uses beyond the narrowing
        # cast, or is an input of this level (owned by the caller)
        use_count = {}
        level_inputs = set(jaxpr.invars) | set(jaxpr.constvars)
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                if not _is_literal(v):
                    use_count[v] = use_count.get(v, 0) + 1
        for v in jaxpr.outvars:
            if not _is_literal(v):
                use_count[v] = use_count.get(v, 0) + 1

        for eqn in jaxpr.eqns:
            self._eqn(eqn, env, use_count, level_inputs, top)

    def _eqn(self, eqn, env, use_count, level_inputs, top):
        prim = eqn.primitive.name
        in_provs = [self._prov(env, v) for v in eqn.invars]

        if prim == "convert_element_type":
            self._convert(eqn, env, in_provs[0], use_count, level_inputs,
                          top)
            return
        if prim in ("pjit", "closed_call", "core_call", "remat",
                    "checkpoint", "custom_vjp_call", "custom_jvp_call",
                    "custom_vjp_call_jaxpr", "scan", "while", "cond"):
            # a narrowed value crossing a call boundary loses sight of
            # its wide root's uses (var identity is per-level): record
            # the liveness fact NOW so a re-widen inside the body still
            # answers the NL102 question (use_count counts the
            # narrowing cast itself once — >1 means another consumer)
            for p in in_provs:
                if p.narrowed_from and p.wide_root is not None and \
                        use_count.get(p.wide_root, 0) > 1:
                    p.wide_live_hint = True
        if prim in ("pjit", "closed_call", "core_call", "remat",
                    "checkpoint", "custom_vjp_call", "custom_jvp_call",
                    "custom_vjp_call_jaxpr"):
            if self._call_boundary(eqn, env, in_provs):
                return
        if prim == "scan":
            self._scan(eqn, env, in_provs)
            return
        if prim == "while":
            self._while(eqn, env, in_provs)
            return
        if prim == "cond":
            self._cond(eqn, env, in_provs)
            return
        if prim == "pallas_call":
            self._fresh_outputs(eqn, env)    # opaque: see module docstring
            return

        # ---- events on ordinary eqns ----
        if prim in SERIAL_REDUCE_PRIMS or prim == "dot_general":
            self._reduce_event(eqn, env, in_provs)
        if prim in TRANSCENDENTAL_PRIMS:
            self._transcendental_event(eqn, in_provs)
        self._quant_use_event(eqn, in_provs)

        # ---- provenance of the outputs ----
        stabilized = self._stabilizes(eqn, env, in_provs)
        for ov in eqn.outvars:
            out_dt = _dtype_of(ov)
            p = Prov(dtype=out_dt, origin=out_dt,
                     quant=out_dt in QUANT_DTYPES)
            if prim in _ELEMENTWISE_LINEAGE:
                # narrow lineage survives elementwise math: the value is
                # still "a narrowed value" until something re-widens it
                for ip in in_provs:
                    if ip.narrowed_from and ip.dtype == out_dt:
                        p = ip.clone(dtype=out_dt)
                        break
                # dequant lineage: math over an un-descaled dequant is
                # still un-descaled (NL301 judges the consumption site)
                for ip in in_provs:
                    if ip.dequant_of is not None:
                        p.dequant_of = ip.dequant_of
                        p.descaled = ip.descaled or p.descaled
                if stabilized or any(ip.stabilized for ip in in_provs
                                     if ip.dtype == out_dt):
                    p.stabilized = True
            if prim == "mul" and self._is_scale_mul(eqn, in_provs):
                p.descaled = True
            if prim == "broadcast_in_dim" and eqn.invars and \
                    not _is_literal(eqn.invars[0]):
                src = in_provs[0]
                p.bcast_src_size = min(
                    _size_of(eqn.invars[0]),
                    src.bcast_src_size or _size_of(eqn.invars[0]))
            if prim == "reduce_max":
                p.from_max = True
            elif prim in ("stop_gradient", "broadcast_in_dim", "reshape",
                          "max") and any(ip.from_max for ip in in_provs):
                p.from_max = True
            if prim in ("exp", "exp2", "expm1"):
                # exp output is positive — a downstream sum of it is a
                # safe softmax denominator when the operand was
                # stabilized
                p.stabilized = in_provs[0].stabilized
            env[ov] = p

        # unknown primitive with sub-jaxprs (no operand mapping known):
        # walk the bodies with fresh provenance so interior rules still
        # see their eqns
        if prim not in ("scan", "while", "cond", "pallas_call"):
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    inner = getattr(sub, "jaxpr", sub)
                    sub_env = {}
                    self._walk(inner, sub_env)

    # ------------------------------------------------------------ converts
    def _convert(self, eqn, env, src, use_count, level_inputs, top):
        new_dt = str(eqn.params.get("new_dtype", ""))
        ov = eqn.outvars[0]
        src_var = eqn.invars[0]
        p = Prov(dtype=new_dt, origin=src.origin or src.dtype,
                 stabilized=src.stabilized)

        p.from_max = src.from_max

        if src.dtype in WIDE_FLOATS and new_dt in NARROW_FLOATS:
            # narrowing: remember the wide root for the round-trip check
            p.narrowed_from = src.dtype
            p.narrow_eqn = eqn
            p.wide_root = src_var
            p.wide_root_is_input = (not _is_literal(src_var)
                                    and src_var in level_inputs)
        elif src.dtype in NARROW_FLOATS and new_dt in WIDE_FLOATS:
            if src.narrowed_from == new_dt:
                root = src.wide_root
                other_uses = 0
                if root is not None and not _is_literal(root):
                    # uses beyond the narrowing cast itself
                    other_uses = use_count.get(root, 0) - 1
                self.result.round_trips.append(RoundTripEvent(
                    widen_eqn=eqn, narrow_eqn=src.narrow_eqn,
                    wide_dtype=new_dt, narrow_dtype=src.dtype,
                    wide_root=root,
                    wide_root_is_input=src.wide_root_is_input,
                    wide_live=(src.wide_root_is_input
                               or src.wide_live_hint
                               or other_uses > 0)))
        elif src.quant and (new_dt in WIDE_FLOATS
                            or new_dt in NARROW_FLOATS):
            # dequantization: the float carries its code lineage until a
            # scale multiply lands
            p.dequant_of = src_var
            p.descaled = False

        if new_dt in QUANT_DTYPES:
            p.quant = True
            # requantization of a dequantized float: the NL302 chain
            if src.dequant_of is not None:
                other = use_count.get(src_var, 0) - 1
                self.result.requants.append(RequantEvent(
                    eqn=eqn, dequant_eqn=src.dequant_of,
                    intermediate_other_uses=max(0, other)))
        env[ov] = p

    # ------------------------------------------------------------ reductions
    def _reduce_event(self, eqn, env, in_provs):
        prim = eqn.primitive.name
        out_dt = _dtype_of(eqn.outvars[0])
        if prim == "dot_general":
            lhs = eqn.invars[0]
            dn = eqn.params.get("dimension_numbers")
            k = 1
            try:
                for d in dn[0][0]:
                    k *= int(lhs.aval.shape[d])
            except Exception:
                k = 0
            op = in_provs[0]
            if in_provs[1].dtype in NARROW_FLOATS and \
                    op.dtype not in NARROW_FLOATS:
                op = in_provs[1]
            pet = eqn.params.get("preferred_element_type")
            widened = out_dt in WIDE_FLOATS or \
                (pet is not None and str(pet) in WIDE_FLOATS)
            self.result.reductions.append(ReduceEvent(
                eqn=eqn, prim=prim, operand_prov=op, reduce_elems=k,
                out_dtype=out_dt, widened=widened))
        else:
            op_v = eqn.invars[0]
            out_v = eqn.outvars[0]
            k = max(1, _size_of(op_v) // max(1, _size_of(out_v)))
            if prim == "cumsum":
                try:
                    ax = int(eqn.params.get("axis", 0))
                    k = int(op_v.aval.shape[ax])
                except Exception:
                    k = max(1, k)
            self.result.reductions.append(ReduceEvent(
                eqn=eqn, prim=prim, operand_prov=in_provs[0],
                reduce_elems=k, out_dtype=out_dt,
                widened=out_dt in WIDE_FLOATS))

    # --------------------------------------------------------- stability
    def _stabilizes(self, eqn, env, in_provs):
        """Does this eqn itself stabilize its output?  sub(x, max-of-
        lineage) and add/max with a small positive eps both count."""
        prim = eqn.primitive.name
        if prim == "sub" and len(eqn.invars) == 2:
            # max-subtraction: the subtrahend derives from a reduce_max
            # (softmax's x - max(x) pattern; jax.nn.softmax emits
            # stop_gradient(reduce_max) — lineage flows through both)
            if in_provs[1].from_max:
                return True
        if prim in ("add", "max") and len(eqn.invars) == 2:
            if any(_eps_literal(v, self.eps_max) for v in eqn.invars):
                return True
        if prim in ("clamp",):
            return True
        return False

    def _transcendental_event(self, eqn, in_provs):
        prim = eqn.primitive.name
        if prim == "div":
            # the denominator is the hazard; literal denominators are a
            # known quantity (a constant cannot be a stray zero)
            den = eqn.invars[1]
            if _is_literal(den):
                return
            p = in_provs[1]
        else:
            if _is_literal(eqn.invars[0]):
                return
            p = in_provs[0]
        if p.dtype not in NARROW_FLOATS:
            return
        self.result.transcendentals.append(TranscendentalEvent(
            eqn=eqn, prim=prim, operand_prov=p, stabilized=p.stabilized))

    # ------------------------------------------------------ quantization
    @staticmethod
    def _eff_size(v, prov):
        """A value's size for the is-it-a-scale judgment: the PRE-
        broadcast element count when jnp broadcasting expanded it to
        the code shape right before the consuming eqn (a per-page
        [pages, heads] scale is tiny; its broadcast copy is not)."""
        n = _size_of(v)
        if prov is not None and prov.bcast_src_size:
            n = min(n, prov.bcast_src_size)
        return n

    def _is_scale_mul(self, eqn, in_provs):
        """mul(dequant, small-float) — a per-tensor/group/page scale is
        orders of magnitude smaller than the codes it rescales."""
        if eqn.primitive.name != "mul" or len(eqn.invars) != 2:
            return False
        a, b = eqn.invars
        pa, pb = in_provs
        for q, s in ((a, b), (b, a)):
            qp = pa if q is a else pb
            sp = pb if q is a else pa
            if qp.dequant_of is None:
                continue
            if _is_literal(s):
                return True
            if self._eff_size(s, sp) * 8 <= max(1, _size_of(q)):
                return True
        return False

    def _quant_use_event(self, eqn, in_provs):
        prim = eqn.primitive.name
        if prim in ("convert_element_type", "mul"):
            return      # the dequant/rescale machinery itself
        out_dt = _dtype_of(eqn.outvars[0]) if eqn.outvars else ""
        is_float_math = prim in ("dot_general", "add", "sub", "div",
                                 "conv_general_dilated", "reduce_sum",
                                 "cumsum", "dot", "exp", "log", "tanh",
                                 "max", "min") or \
            ("float" in out_dt and prim not in
             ("broadcast_in_dim", "reshape", "transpose", "slice",
              "gather", "dynamic_slice", "concatenate", "squeeze",
              "pad", "select_n", "dynamic_update_slice", "iota",
              "scatter", "scatter-add", "rev", "copy",
              "stop_gradient"))
        if not is_float_math:
            return
        small = [(v, p) for v, p in zip(eqn.invars, in_provs)
                 if _is_literal(v) or "float" in _dtype_of(v)]
        for v, p in zip(eqn.invars, in_provs):
            raw = p.quant and p.dtype in QUANT_DTYPES
            undescaled = p.dequant_of is not None and not p.descaled
            if not raw and not undescaled:
                continue
            # int8/uint8 feeding pure integer/index math is a mask or
            # an id, not a code — only float-consuming math counts
            if raw and p.dtype in ("int8", "uint8") and \
                    "float" not in out_dt:
                continue
            has_scale = any(
                s is not v and (_is_literal(s)
                                or self._eff_size(s, sp) * 8
                                <= max(1, _size_of(v)))
                for s, sp in small)
            self.result.quant_uses.append(QuantUseEvent(
                eqn=eqn, prim=prim, operand=v, operand_dtype=p.dtype,
                raw=raw, has_scale_operand=has_scale))

    # --------------------------------------------------- call boundaries
    def _map_into(self, sub, outer_provs):
        """env for a sub-jaxpr whose invars align with `outer_provs`."""
        inner = getattr(sub, "jaxpr", sub)
        env = {}
        for cv, c in zip(inner.constvars,
                         getattr(sub, "consts", []) or []):
            dt = str(getattr(c, "dtype", "")) or _dtype_of(cv)
            env[cv] = Prov(dtype=dt, origin=dt,
                           quant=dt in QUANT_DTYPES)
        for iv, p in zip(inner.invars, outer_provs):
            env[iv] = p.clone(dtype=_dtype_of(iv) or p.dtype)
        for iv in inner.invars[len(outer_provs):]:
            dt = _dtype_of(iv)
            env[iv] = Prov(dtype=dt, origin=dt,
                           quant=dt in QUANT_DTYPES)
        return env, inner

    def _call_boundary(self, eqn, env, in_provs):
        sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") \
            or eqn.params.get("fun_jaxpr")
        if sub is None:
            return False
        num_consts = int(eqn.params.get("num_consts", 0) or 0)
        sub_env, inner = self._map_into(sub, in_provs[num_consts:]
                                        if num_consts else in_provs)
        self._walk(inner, sub_env)
        for ov, iv in zip(eqn.outvars, inner.outvars):
            p = sub_env.get(iv) if not _is_literal(iv) else None
            env[ov] = (p.clone(dtype=_dtype_of(ov)) if p is not None
                       else Prov(dtype=_dtype_of(ov),
                                 origin=_dtype_of(ov)))
        return True

    def _scan(self, eqn, env, in_provs):
        body = eqn.params.get("jaxpr")
        if body is None:
            self._fresh_outputs(eqn, env)
            return
        inner = getattr(body, "jaxpr", body)
        num_consts = int(eqn.params.get("num_consts", 0))
        num_carry = int(eqn.params.get("num_carry", 0))
        sub_env, inner = self._map_into(body, in_provs)
        self._walk(inner, sub_env)
        # NL202: a narrow carry the body widens for its math
        carries = inner.invars[num_consts:num_consts + num_carry]
        for slot, cv in enumerate(carries):
            cdt = _dtype_of(cv)
            if cdt not in NARROW_FLOATS:
                continue
            for beqn in inner.eqns:
                if beqn.primitive.name == "convert_element_type" and \
                        cv in beqn.invars and \
                        str(beqn.params.get("new_dtype", "")) in \
                        WIDE_FLOATS:
                    self.result.scan_carries.append(ScanCarryEvent(
                        eqn=eqn, slot=slot, carry_dtype=cdt,
                        body_dtype=str(beqn.params["new_dtype"])))
                    break
        for ov, iv in zip(eqn.outvars, inner.outvars):
            p = sub_env.get(iv) if not _is_literal(iv) else None
            env[ov] = (p.clone(dtype=_dtype_of(ov)) if p is not None
                       else Prov(dtype=_dtype_of(ov),
                                 origin=_dtype_of(ov)))

    def _while(self, eqn, env, in_provs):
        body = eqn.params.get("body_jaxpr")
        cond = eqn.params.get("cond_jaxpr")
        bn = int(eqn.params.get("body_nconsts", 0))
        cn = int(eqn.params.get("cond_nconsts", 0))
        if cond is not None:
            sub_env, inner = self._map_into(cond, in_provs[:cn] +
                                            in_provs[cn + bn:])
            self._walk(inner, sub_env)
        if body is None:
            self._fresh_outputs(eqn, env)
            return
        sub_env, inner = self._map_into(body, in_provs[cn:])
        self._walk(inner, sub_env)
        for ov, iv in zip(eqn.outvars, inner.outvars):
            p = sub_env.get(iv) if not _is_literal(iv) else None
            env[ov] = (p.clone(dtype=_dtype_of(ov)) if p is not None
                       else Prov(dtype=_dtype_of(ov),
                                 origin=_dtype_of(ov)))

    def _cond(self, eqn, env, in_provs):
        branches = eqn.params.get("branches", ())
        outs = None
        for b in branches:
            sub_env, inner = self._map_into(b, in_provs[1:])
            self._walk(inner, sub_env)
            if outs is None:
                outs = [sub_env.get(iv) if not _is_literal(iv) else None
                        for iv in inner.outvars]
        for ov, p in zip(eqn.outvars, outs or []):
            env[ov] = (p.clone(dtype=_dtype_of(ov)) if p is not None
                       else Prov(dtype=_dtype_of(ov),
                                 origin=_dtype_of(ov)))
        if outs is None:
            self._fresh_outputs(eqn, env)

    def _fresh_outputs(self, eqn, env):
        for ov in eqn.outvars:
            dt = _dtype_of(ov)
            env[ov] = Prov(dtype=dt, origin=dt,
                           quant=dt in QUANT_DTYPES)
