"""Static VMEM footprint model for Pallas TPU kernels (kernlint KL102).

XLA never tells you a kernel's VMEM bill until Mosaic compiles it on
real silicon — by then the trace, lowering and compile time are spent
and the failure mode is a cryptic allocation error (or a silent spill).
This module prices a ``pallas_call`` eqn *at trace time* from exactly
the facts the eqn already carries:

- every in/out :class:`BlockMapping`'s ``block_shape`` + array dtype,
  padded up to the dtype's native VMEM tile ((8,128) f32, (16,128)
  bf16, (32,128) int8/fp8 — sublane = 32 // itemsize, lane = 128; see
  the TPU Pallas guide's tiling table);
- **double-buffering**: the Pallas pipeline keeps two copies of every
  grid-iterated block so the next block's DMA overlaps this block's
  compute — any call with more than one grid step pays 2x per operand
  block (a single-step call has nothing to overlap);
- scratch operands (``pltpu.VMEM`` / ``scratch_shapes``), read off the
  tail of the kernel jaxpr's invars — allocated once, never
  double-buffered.

The estimate is deliberately a *lower bound* sharpened to be useful:
Mosaic's own spills (register pressure, retiling copies) come on top,
so a kernel whose static estimate already exceeds the per-core budget
is guaranteed trouble.  Deterministic by construction — the same eqn
always prices the same, which is what the kernlint baseline gates on.

Pure stdlib at module level (the eqn objects bring jax types with
them); unit-pinned by hand-computed footprints in
tests/test_kernlint.py.
"""
from __future__ import annotations

from dataclasses import dataclass, field

__all__ = [
    "LANE", "VmemEstimate", "native_tile", "padded_block_bytes",
    "estimate_vmem", "sublane",
]

LANE = 128                      # minor-most tile dim, every dtype

_MIB = 1 << 20


def sublane(dtype):
    """Second-minor tile dim for `dtype`: 32 // itemsize, floored at 8
    (f32 tiles are (8,128); bf16 (16,128); int8/fp8 (32,128))."""
    itemsize = int(getattr(dtype, "itemsize", 4) or 4)
    return max(8, 32 // max(1, itemsize))


def native_tile(dtype):
    """The dtype's native (sublane, lane) VMEM tile."""
    return (sublane(dtype), LANE)


def _ceil_to(n, m):
    return -(-int(n) // int(m)) * int(m)


def _int_dims(block_shape):
    """Block dims as ints: Pallas marks squeezed/mapped dims with a
    non-int sentinel — those occupy one element of the block."""
    out = []
    for d in block_shape or ():
        try:
            out.append(max(1, int(d)))
        except (TypeError, ValueError):
            out.append(1)
    return out


def padded_block_bytes(block_shape, dtype):
    """Bytes one VMEM copy of this block occupies: the two minor dims
    round up to the dtype's native tile (Mosaic stores nothing
    smaller), every major dim counts as-is."""
    dims = _int_dims(block_shape)
    itemsize = int(getattr(dtype, "itemsize", 4) or 4)
    if not dims:
        return itemsize
    dims[-1] = _ceil_to(dims[-1], LANE)
    if len(dims) >= 2:
        dims[-2] = _ceil_to(dims[-2], sublane(dtype))
    n = 1
    for d in dims:
        n *= d
    return n * itemsize


@dataclass
class VmemEstimate:
    """Itemized static VMEM bill of one ``pallas_call``."""

    grid: tuple = ()
    # (origin, one-copy bytes, buffered bytes) per in/out block
    blocks: list = field(default_factory=list)
    scratch_bytes: int = 0
    double_buffered: bool = False

    @property
    def block_bytes(self):
        return sum(b for _, _, b in self.blocks)

    @property
    def total_bytes(self):
        return self.block_bytes + self.scratch_bytes

    def describe(self):
        mib = self.total_bytes / _MIB
        buf = "x2 double-buffered" if self.double_buffered else "x1"
        return (f"{mib:.2f} MiB ({len(self.blocks)} block buffer(s) "
                f"{buf} + {self.scratch_bytes / _MIB:.2f} MiB scratch)")

    def to_dict(self):
        return {
            "grid": [int(g) for g in self.grid],
            "blocks": [{"origin": o, "bytes": b, "buffered_bytes": bb}
                       for o, b, bb in self.blocks],
            "scratch_bytes": self.scratch_bytes,
            "block_bytes": self.block_bytes,
            "total_bytes": self.total_bytes,
            "double_buffered": self.double_buffered,
        }


def _grid_steps(grid):
    n = 1
    for d in grid or ():
        try:
            n *= max(1, int(d))
        except (TypeError, ValueError):
            pass
    return n


def estimate_vmem(eqn):
    """Price one ``pallas_call`` eqn; returns a :class:`VmemEstimate`
    (zeros when the eqn's params are unreadable — never raises)."""
    est = VmemEstimate()
    gm = eqn.params.get("grid_mapping")
    if gm is None:
        return est
    grid = tuple(getattr(gm, "grid", ()) or ())
    est.grid = grid
    est.double_buffered = _grid_steps(grid) > 1
    factor = 2 if est.double_buffered else 1
    for bm in getattr(gm, "block_mappings", ()) or ():
        sd = getattr(bm, "array_shape_dtype", None)
        dtype = getattr(sd, "dtype", None)
        one = padded_block_bytes(getattr(bm, "block_shape", ()), dtype)
        origin = str(getattr(bm, "origin", "") or "")
        est.blocks.append((origin, one, one * factor))
    # scratch refs are the tail of the kernel jaxpr invars, after the
    # scalar-prefetch operands and the in/out block refs
    n_scratch = int(getattr(gm, "num_scratch_operands", 0) or 0)
    if n_scratch:
        kjaxpr = eqn.params.get("jaxpr")
        kjaxpr = getattr(kjaxpr, "jaxpr", kjaxpr)
        invars = list(getattr(kjaxpr, "invars", ()) or ())
        for v in invars[len(invars) - n_scratch:]:
            aval = getattr(v, "aval", None)
            shape = tuple(getattr(aval, "shape", ()) or ())
            dtype = getattr(aval, "dtype", None)
            est.scratch_bytes += padded_block_bytes(shape, dtype)
    return est
