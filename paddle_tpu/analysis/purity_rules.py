"""tracelint TL1xx: host syncs and trace-time side effects.

Everything reached from a `@to_static` entry runs under one jax trace:
host syncs (`.numpy()`, `float(t)`) either raise a concretization error
or silently freeze a value at trace time, and side effects (`print`,
appending to an outer list, host randomness) run ONCE while tracing
instead of once per compiled step.  Tensor-likeness is the heuristic
`visitor.TensorEnv` dataflow — over-approximate by design; the baseline
absorbs reviewed-and-accepted findings.
"""
from __future__ import annotations

import ast

from paddle_tpu.analysis.rules import message_for
from paddle_tpu.analysis.visitor import (
    Finding, TensorEnv, _dotted, walk_same_scope,
)

HOST_SYNC_METHODS = {"numpy", "item", "tolist"}
CONCRETIZERS = {"float", "int", "bool"}
NP_HOST_FUNCS = {"array", "asarray", "asanyarray", "ascontiguousarray"}
# dotted call prefixes evaluated on the HOST at trace time
UNTRACED_SOURCES = (
    "np.random.", "numpy.random.", "random.", "time.time", "time.monotonic",
    "time.perf_counter", "datetime.",
)


def _finding(index, node, code, detail=""):
    return Finding(path=index.path, line=node.lineno,
                   col=getattr(node, "col_offset", 0), code=code,
                   message=message_for(code, detail=detail))


def _local_stores(fdef):
    names = set()
    for n in ast.walk(fdef):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            names.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.add(n.name)
    a = fdef.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs +
                ([a.vararg] if a.vararg else []) +
                ([a.kwarg] if a.kwarg else [])):
        names.add(arg.arg)
    return names


def check_purity(index, reached):
    out = []
    for fi in reached:
        fdef = fi.node
        env = TensorEnv(fdef, is_entry=True)
        local = _local_stores(fdef)
        globals_decl = set()
        for n in walk_same_scope(fdef):
            if isinstance(n, ast.Global):
                globals_decl.update(n.names)

        for n in walk_same_scope(fdef):
            if not isinstance(n, ast.Call):
                if isinstance(n, ast.Assign):
                    # store to a declared-global name with a tensorish RHS
                    for t in n.targets:
                        if isinstance(t, ast.Name) and t.id in globals_decl \
                                and env.is_tensorish(n.value):
                            out.append(_finding(
                                index, n, "TL106",
                                detail=f"global `{t.id}`"))
                continue
            f = n.func
            # ---- TL101: t.numpy() / t.item() / t.tolist() ----
            if isinstance(f, ast.Attribute) and f.attr in HOST_SYNC_METHODS \
                    and env.is_tensorish(f.value):
                out.append(_finding(index, n, "TL101", detail=f.attr))
                continue
            # ---- TL102: float(t) / int(t) / bool(t) ----
            if isinstance(f, ast.Name) and f.id in CONCRETIZERS and n.args \
                    and env.is_tensorish(n.args[0]):
                out.append(_finding(index, n, "TL102", detail=f.id))
                continue
            dotted = _dotted(f)
            # ---- TL103: np.array(t) & friends ----
            root, _, tail = dotted.partition(".")
            if root in ("np", "numpy") and tail in NP_HOST_FUNCS and \
                    n.args and env.is_tensorish(n.args[0]):
                out.append(_finding(index, n, "TL103", detail=tail))
                continue
            # ---- TL104: print(tensor) ----
            if isinstance(f, ast.Name) and f.id == "print" and any(
                    env.is_tensorish(a) for a in n.args):
                out.append(_finding(index, n, "TL104"))
                continue
            # ---- TL105: host randomness / clocks ----
            if dotted and any(dotted == u.rstrip(".") or
                              dotted.startswith(u) for u in UNTRACED_SOURCES):
                out.append(_finding(index, n, "TL105", detail=dotted))
                continue
            # ---- TL106: mutating an outer list/set/dict with tensors ----
            if isinstance(f, ast.Attribute) and \
                    f.attr in ("append", "extend", "add", "insert",
                               "update", "setdefault") and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id not in local and \
                    any(env.is_tensorish(a) for a in n.args):
                out.append(_finding(
                    index, n, "TL106",
                    detail=f"outer `{f.value.id}.{f.attr}(...)`"))
    return out
