"""tracelint TL0xx / TL3xx: conversion-subset and recompile hazards.

These rules re-state, ahead of trace, exactly the bail conditions
`jit/dy2static.py` applies during transform: a loop containing `return`,
`break`/`continue` in a non-range `for`, or a loop `else:` clause is
left as plain Python — correct eagerly, but a tensor-valued condition
there surfaces as a trace-time error.  The runtime guards in dy2static
raise the same codes (via `rules.TraceHazardError`); this pass finds
them before the expensive trace.
"""
from __future__ import annotations

import ast

from paddle_tpu.analysis.rules import message_for
from paddle_tpu.analysis.visitor import (
    Finding, is_to_static_decorator, walk_same_scope as _walk_same_scope,
)


def _finding(index, node, code, detail=""):
    return Finding(path=index.path, line=node.lineno,
                   col=getattr(node, "col_offset", 0), code=code,
                   message=message_for(code, detail=detail))


def _is_range_for(node):
    it = node.iter
    return (isinstance(node, ast.For) and isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name) and it.func.id == "range"
            and not it.keywords and 1 <= len(it.args) <= 3
            and isinstance(node.target, ast.Name))


def _loop_level_exits(loop):
    """break/continue/return belonging to THIS loop (not nested loops;
    returns DO escape nested loops)."""
    brk, ret = [], []
    stack = [(s, True) for s in loop.body]
    while stack:
        n, own = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(n, (ast.Break, ast.Continue)):
            if own:
                brk.append(n)
            continue
        if isinstance(n, ast.Return):
            ret.append(n)
            continue
        nested = isinstance(n, (ast.For, ast.While))
        for c in ast.iter_child_nodes(n):
            stack.append((c, own and not nested))
    return brk, ret


def check_subset(index, reached):
    """TL001/TL002/TL003/TL004 over every function reached from an entry."""
    out = []
    for fi in reached:
        fdef = fi.node
        if isinstance(fdef, ast.AsyncFunctionDef):
            out.append(_finding(index, fdef, "TL004"))
            continue
        for n in _walk_same_scope(fdef):
            if isinstance(n, (ast.Yield, ast.YieldFrom)):
                out.append(_finding(index, fdef, "TL004"))
                break
        for n in _walk_same_scope(fdef):
            if not isinstance(n, (ast.For, ast.While)):
                continue
            brk, ret = _loop_level_exits(n)
            if n.orelse:
                out.append(_finding(index, n, "TL003"))
            if ret:
                out.append(_finding(
                    index, ret[0], "TL001",
                    detail=f" (loop at line {n.lineno})"))
            if brk and isinstance(n, ast.For) and not _is_range_for(n):
                out.append(_finding(
                    index, brk[0], "TL002",
                    detail=f" (loop at line {n.lineno})"))
    return out


def check_recompile(index, reached):
    """TL301 (mutable default on an entry), TL302 (to_static in a loop)."""
    out = []
    for fi in reached:
        if not fi.is_entry:
            continue
        a = fi.node.args
        for d in (a.defaults or []) + [d for d in (a.kw_defaults or [])
                                       if d is not None]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                kind = type(d).__name__.lower()
                out.append(_finding(index, d, "TL301",
                                    detail=f"(a {kind} literal)"))
    # to_static applied under a loop (call form, or a decorated def whose
    # definition re-executes per iteration).  Whole-file mode scans every
    # function (recompile storms live in glue code, not entries); a
    # PARTIAL lint (one explicit root, to_static(check=True)) narrows to
    # module-level code plus the root's reach so unrelated functions
    # don't warn on every wrap.
    reached_ids = {id(fi.node) for fi in reached}

    def scan(node, in_loop, active):
        for c in ast.iter_child_nodes(node):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if active and in_loop and any(is_to_static_decorator(d)
                                              for d in c.decorator_list):
                    out.append(_finding(index, c, "TL302"))
                scan(c, False,  # a new scope resets loop context
                     not index.partial or id(c) in reached_ids)
                continue
            if active and in_loop and isinstance(c, ast.Call) and \
                    is_to_static_decorator(c.func):
                out.append(_finding(index, c, "TL302"))
            scan(c, in_loop or isinstance(c, (ast.For, ast.While)), active)

    scan(index.tree, False, True)
    return out
