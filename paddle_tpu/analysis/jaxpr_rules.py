"""tracelint TL4xx: post-trace lint of the emitted jaxpr.

The AST pass (subset/purity rules) runs before any trace; this pass runs
AFTER `to_static` traces the step function and inspects the actual
program XLA will compile: unintended f32->f64 widenings (TL401), large
host constants baked into the executable (TL402), and collectives
issued against no / the wrong mesh axis (TL403/TL404).  Wired in via
`to_static(check=True)` and importable directly for tools.

Dtype-promotion policy comes from `core/dispatch.py`
(`default_float_dtype` / `wide_dtype_allowed_ops`), so ops that widen
deliberately can register themselves once and stay unflagged everywhere.
"""
from __future__ import annotations

from paddle_tpu.analysis.rules import message_for
from paddle_tpu.analysis.visitor import Finding

# primitive name -> param key holding the axis name(s)
COLLECTIVE_PRIMS = {
    "psum": "axes", "pmin": "axes", "pmax": "axes",
    "all_gather": "axis_name", "all_to_all": "axis_name",
    "ppermute": "axis_name", "reduce_scatter": "axis_name",
    "axis_index": "axis_name", "pbroadcast": "axes",
}

WIDE_DTYPES = ("float64", "complex128")

LARGE_CONST_BYTES = 1 << 20  # 1 MiB


def _iter_eqns(jaxpr):
    """All eqns of a (Closed)Jaxpr, recursing into sub-jaxprs
    (pjit/while/cond/scan bodies)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(v):
    if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
        return [v]
    if isinstance(v, (list, tuple)):
        out = []
        for x in v:
            out.extend(_sub_jaxprs(x))
        return out
    return []


def _axis_names(eqn):
    key = COLLECTIVE_PRIMS.get(eqn.primitive.name)
    if key is None:
        return None
    v = eqn.params.get(key)
    if isinstance(v, (list, tuple)):
        return tuple(a for a in v if isinstance(a, str))
    return (v,) if isinstance(v, str) else ()


def check_jaxpr(closed_jaxpr, where="<traced function>",
                large_const_bytes=LARGE_CONST_BYTES):
    """Lint one ClosedJaxpr; returns [Finding] (path = `where`)."""
    from paddle_tpu.core import dispatch
    from paddle_tpu.distributed import mesh as dmesh

    findings = []

    def emit(code, detail):
        findings.append(Finding(path=where, line=0, col=0, code=code,
                                message=message_for(code, detail=detail)))

    # ---- TL401: widenings past the default float ----
    # Report only INTRODUCTION points (wide output, no wide input) so a
    # single upcast yields one finding at its origin, not one per
    # downstream primitive the f64 flows through.  An allowlisted
    # introducer silences its whole chain.
    default_float = dispatch.default_float_dtype()
    allowed = dispatch.wide_dtype_allowed_ops()
    if default_float == "float32":
        def _wide(v):
            return str(getattr(getattr(v, "aval", None), "dtype", "")) \
                in WIDE_DTYPES

        intro_any, intro_flagged = {}, {}
        for eqn in _iter_eqns(closed_jaxpr):
            out_dt = next(
                (str(ov.aval.dtype) for ov in eqn.outvars if _wide(ov)),
                None)
            if out_dt is None or any(_wide(iv) for iv in eqn.invars):
                continue
            intro_any.setdefault(eqn.primitive.name, out_dt)
            if eqn.primitive.name not in allowed:
                intro_flagged.setdefault(eqn.primitive.name, out_dt)
        for prim, dt in sorted(intro_flagged.items()):
            emit("TL401", f"{dt} (first introduced by `{prim}`)")
        if not intro_any:
            # wide values can also ENTER the program (traced input or
            # baked constant) without any introducing eqn
            inner = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
            entering = [str(v.aval.dtype) for v in inner.invars if _wide(v)]
            entering += [str(getattr(c, "dtype", ""))
                         for c in getattr(closed_jaxpr, "consts", []) or []
                         if str(getattr(c, "dtype", "")) in WIDE_DTYPES]
            if entering:
                emit("TL401",
                     f"{entering[0]} (entering as a traced input or "
                     f"constant)")

    # ---- TL402: large constants baked into the program ----
    for const in getattr(closed_jaxpr, "consts", []) or []:
        nbytes = getattr(const, "nbytes", 0)
        if nbytes and nbytes >= large_const_bytes:
            shape = tuple(getattr(const, "shape", ()))
            dt = str(getattr(const, "dtype", "?"))
            emit("TL402",
                 f"{nbytes / (1 << 20):.1f} MiB ({dt}{list(shape)})")

    # ---- TL403/TL404: collectives vs the mesh ----
    mesh = dmesh.get_mesh()
    mesh_axes = tuple(mesh.axis_names) if mesh is not None else ()
    seen = set()
    for eqn in _iter_eqns(closed_jaxpr):
        names = _axis_names(eqn)
        if not names:
            continue  # not a collective, or positional (unnamed) axes
        key = (eqn.primitive.name, names)
        if key in seen:
            continue
        seen.add(key)
        if mesh is None:
            emit("TL403", f"{eqn.primitive.name}(axis={list(names)})")
        else:
            bad = [n for n in names if isinstance(n, str)
                   and n not in mesh_axes]
            if bad:
                emit("TL404",
                     f"{eqn.primitive.name}(axis={bad}) vs mesh axes "
                     f"{list(mesh_axes)}")
    return findings
