"""tracelint reporting: text / JSON output and the repo baseline.

The baseline lets the analyzer self-host over a codebase with known,
reviewed findings: each finding is fingerprinted by
(path, code, hash-of-stripped-source-line) — line NUMBERS move on every
edit, line TEXT rarely does — and the baseline stores a count per
fingerprint.  `--check` mode reports only findings whose fingerprint
count EXCEEDS the baseline, so new hazards fail the gate while the
accepted backlog stays quiet.
"""
from __future__ import annotations

import hashlib
import json
from collections import Counter

BASELINE_VERSION = 1


def fingerprint(finding):
    h = hashlib.sha1(
        finding.source_line.strip().encode("utf-8", "replace")).hexdigest()[:12]
    return f"{finding.path}::{finding.code}::{h}"


def to_json(findings, extra=None):
    doc = {"version": BASELINE_VERSION,
           "count": len(findings),
           "findings": [f.to_dict() for f in findings]}
    if extra:
        doc.update(extra)
    return doc


def format_text(findings, show_source=True):
    lines = []
    for f in findings:
        lines.append(f.format())
        if show_source and f.source_line:
            lines.append(f"    {f.source_line}")
    return "\n".join(lines)


def write_baseline(findings, path):
    counts = Counter(fingerprint(f) for f in findings)
    doc = {"version": BASELINE_VERSION,
           "fingerprints": dict(sorted(counts.items()))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return doc


def load_baseline(path):
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return {}
    return dict(doc.get("fingerprints", {}))


def diff_vs_baseline(findings, baseline):
    """Findings above the baselined count per fingerprint (the NEW ones)."""
    budget = Counter(baseline)
    new = []
    for f in findings:
        fp = fingerprint(f)
        if budget[fp] > 0:
            budget[fp] -= 1
        else:
            new.append(f)
    return new


def summarize(findings):
    by_code = Counter(f.code for f in findings)
    return ", ".join(f"{c}×{n}" for c, n in sorted(by_code.items())) or "none"
