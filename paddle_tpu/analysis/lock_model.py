"""racelint's world model: thread roots, lock identities, lock sets.

The host runtime is concurrent the way most Python runtimes are: a
main thread driving the public API, a handful of daemon worker loops
(`threading.Thread(target=...)`), executor pools, signal handlers, and
fork-process DataLoader workers.  Statically we recover that structure
per module and stitch it package-wide:

- **Lock identities.**  ``self._lock = threading.Lock()`` inside class
  ``C`` of module ``M`` names the lock ``M.C._lock``; a module-level
  ``_lock = threading.Lock()`` names ``M._lock``; a lock bound to a
  function local names ``M.<func>.<name>`` (per-call, but its ordering
  constraints are still real).  Condition/Semaphore count as locks;
  Queue/Event/deque and friends are classified *thread-safe* so their
  use never demands a guard.
- **Thread roots.**  Functions reaching the runtime from somewhere
  other than the main call stack: ``threading.Thread(target=f)``,
  ``pool.submit(f)``, ``signal.signal(sig, h)``, multiprocessing
  ``Process(target=f)`` (discovered, but fork workers do not share the
  parent heap so they opt out of shared-state rules), and handler
  objects registered process-wide via ``install(self)``.  Every public
  method of a class owning a root is additionally a *main-thread* root
  — the public API is exactly what the main thread calls.
- **Per-statement lock sets.**  A structural walk of each function
  tracks the set of locks held at every statement (``with lock:``
  blocks and paired ``acquire()``/``release()`` calls), tagging every
  ``self.X`` / module-global access, every blocking call, and every
  nested acquisition (the acquired-while-holding edge set RL102 runs
  cycle detection over).

Pure stdlib — no jax import; the CLI models the whole package in a few
seconds.  :mod:`race_rules` turns this model into RLxxx findings.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from paddle_tpu.analysis.visitor import (ModuleIndex, _dotted,
                                         walk_same_scope)

# constructor (last dotted segment) -> is it a lock-like / safe type?
LOCK_TYPES = {"Lock", "RLock", "Condition", "Semaphore",
              "BoundedSemaphore"}
# types whose cross-thread use is safe by design: no lock needed, and
# no RL101 finding for sharing them
SAFE_TYPES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
              "JoinableQueue", "Event", "Barrier", "local"}

# method names that MUTATE the object they are called on (used to
# classify `self.X.append(...)` as a write to X)
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "update", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "setdefault",
    "sort", "reverse", "put", "put_nowait",
}

MAIN = "<main>"


@dataclass
class LockInfo:
    lock_id: str
    kind: str                   # Lock / RLock / Condition / ...
    path: str
    line: int


@dataclass
class ThreadRoot:
    root_id: str                # "thread:M.C._writer_loop", "signal:..."
    kind: str                   # thread | executor | signal | process
                                # | installed
    target: object              # FunctionInfo or None
    path: str = ""
    line: int = 0
    daemon: bool = False
    joined: bool = False        # a .join() on the thread was found
    shares_memory: bool = True  # fork Process roots: False


@dataclass
class Access:
    attr: str                   # "M.C.X" or "M.X"
    kind: str                   # read | write
    locks: frozenset
    line: int
    col: int
    in_init: bool = False
    func: object = None         # FunctionInfo


@dataclass
class BlockingCall:
    desc: str
    locks: frozenset
    line: int
    col: int


@dataclass
class Edge:
    held: str
    acquired: str
    line: int

    def key(self):
        return (self.held, self.acquired)


@dataclass
class FuncModel:
    fi: object                          # visitor.FunctionInfo
    owner: str                          # "M.C" or "M"
    accesses: list = field(default_factory=list)
    blocking: list = field(default_factory=list)    # held-set nonempty
    blocking_any: list = field(default_factory=list)  # regardless of held
    edges: list = field(default_factory=list)           # [Edge]
    direct_acquires: set = field(default_factory=set)
    # calls to same-class/module functions made while holding locks:
    # [(callee FuncModel-key, frozenset(held), line)]
    held_calls: list = field(default_factory=list)
    # every resolvable same-scope call: [(callee qualname, line)]
    calls: list = field(default_factory=list)
    all_blocking: list = field(default_factory=list)    # transitive
    acquire_sites: list = field(default_factory=list)   # [(lock_id, line)]
    contexts: set = field(default_factory=set)          # filled by closure
    all_acquires: set = field(default_factory=set)      # transitive
    is_root_target: bool = False


@dataclass
class TOCTOU:
    attr: str
    locks: frozenset
    line: int
    col: int
    func: object = None


class ClassModel:
    """Everything racelint knows about one class."""

    def __init__(self, module, name, node, path):
        self.module = module
        self.name = name
        self.node = node
        self.path = path
        self.qual = f"{module}.{name}"
        self.locks = {}             # attr name -> LockInfo
        self.safe_attrs = set()     # Queue/Event/... typed attributes
        self.roots = []             # [ThreadRoot] whose target is a method
        self.funcs = {}             # qualname -> FuncModel
        self.toctou = []            # [TOCTOU]
        self.executors = []         # [(attr_or_name, line, has_shutdown)]
        self.thread_creations = []  # [(line, daemon, joined, target_qn)]


class ModuleModel:
    """One parsed module: its classes, module-level locks/globals/roots."""

    def __init__(self, path, modname, source, tree):
        self.path = path
        self.modname = modname
        self.source = source
        self.index = ModuleIndex(path, source, tree)
        self.classes = {}           # class name -> ClassModel
        self.locks = {}             # module-level: name -> LockInfo
        self.safe_globals = set()
        self.shared_globals = set() # names written via `global` / subscript
        self.roots = []             # module-level-target roots
        self.funcs = {}             # qualname -> FuncModel (module-level fns)
        self.toctou = []
        self.executors = []
        self.thread_creations = []

    # ---- name plumbing -------------------------------------------------
    def owner_class(self, fi):
        """ClassModel a function belongs to (methods AND their nested
        closures, via the qualname prefix), or None."""
        head = fi.qualname.split(".")[0]
        return self.classes.get(head)

    def func_model(self, fi):
        cm = self.owner_class(fi)
        table = cm.funcs if cm is not None else self.funcs
        fm = table.get(fi.qualname)
        if fm is None:
            owner = cm.qual if cm is not None else self.modname
            fm = FuncModel(fi=fi, owner=owner)
            table[fi.qualname] = fm
        return fm

    def all_funcs(self):
        for fm in self.funcs.values():
            yield fm
        for cm in self.classes.values():
            for fm in cm.funcs.values():
                yield fm


def _ctor_kind(node):
    """'Lock' / 'Queue' / ... when `node` is a call to a known
    lock/safe-type constructor, else None."""
    if not isinstance(node, ast.Call):
        return None
    name = _dotted(node.func).split(".")[-1]
    if name in LOCK_TYPES or name in SAFE_TYPES:
        return name
    if name == "deque":
        return "deque"
    return None


def _is_self_attr(node):
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


class _FuncWalker:
    """Walks ONE function body in statement order, tracking the set of
    held locks, and records accesses / blocking calls / lock-order
    edges into the FuncModel."""

    def __init__(self, mm, fm):
        self.mm = mm
        self.fm = fm
        self.cm = mm.owner_class(fm.fi)
        self.local_locks = {}       # local name -> lock_id
        self.in_init = fm.fi.qualname.endswith("__init__") \
            and "." in fm.fi.qualname

    # ---- lock identity resolution ----
    def resolve_lock(self, node):
        """lock_id for an expression naming a known lock, else None."""
        if _is_self_attr(node) and self.cm is not None:
            info = self.cm.locks.get(node.attr)
            return info.lock_id if info is not None else None
        if isinstance(node, ast.Name):
            if node.id in self.local_locks:
                return self.local_locks[node.id]
            info = self.mm.locks.get(node.id)
            return info.lock_id if info is not None else None
        return None

    # ---- shared-attr resolution ----
    def resolve_attr(self, node):
        """Qualified shared-state id for `self.X` / shared global X."""
        if _is_self_attr(node) and self.cm is not None:
            name = node.attr
            if name in self.cm.locks or name in self.cm.safe_attrs:
                return None
            return f"{self.cm.qual}.{name}"
        if isinstance(node, ast.Name):
            if node.id in self.mm.shared_globals \
                    and node.id not in self.mm.locks \
                    and node.id not in self.mm.safe_globals:
                return f"{self.mm.modname}.{node.id}"
        return None

    # ---- the walk ----
    def walk(self):
        node = self.fm.fi.node
        # record local lock assignments up-front (closures defined
        # BEFORE the assignment still see the name at call time); own
        # scope only — nested functions inherit via the builder
        for n in walk_same_scope(node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                kind = _ctor_kind(n.value)
                if kind in LOCK_TYPES:
                    qn = self.fm.fi.qualname
                    self.local_locks[n.targets[0].id] = \
                        f"{self.mm.modname}.{qn}.{n.targets[0].id}"
        self._stmts(node.body, frozenset())

    def _stmts(self, stmts, held):
        """Process a statement list with `held` locks; returns the held
        set at the end (acquire()/release() pairs mutate it)."""
        held = set(held)
        for stmt in stmts:
            held = self._stmt(stmt, held)
        return frozenset(held)

    def _stmt(self, stmt, held):
        held = set(held)
        if isinstance(stmt, ast.With):
            inner = set(held)
            for item in stmt.items:
                lid = self.resolve_lock(item.context_expr)
                self._expr(item.context_expr, frozenset(inner))
                if lid is not None:
                    self._acquire(lid, frozenset(inner), stmt.lineno)
                    inner.add(lid)
            self._stmts(stmt.body, frozenset(inner))
            return held
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return held     # nested defs walk via their own FuncModel
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            f = call.func
            if isinstance(f, ast.Attribute):
                lid = self.resolve_lock(f.value)
                if lid is not None and f.attr == "acquire":
                    self._acquire(lid, frozenset(held), stmt.lineno)
                    held.add(lid)
                    self._expr_children(call, frozenset(held))
                    return held
                if lid is not None and f.attr == "release":
                    held.discard(lid)
                    return held
            self._expr(stmt.value, frozenset(held))
            return held
        if isinstance(stmt, ast.If):
            self._maybe_toctou(stmt, frozenset(held))
            self._expr(stmt.test, frozenset(held))
            self._stmts(stmt.body, frozenset(held))
            self._stmts(stmt.orelse, frozenset(held))
            return held
        if isinstance(stmt, (ast.For, ast.While)):
            for f_ in ("test", "iter"):
                e = getattr(stmt, f_, None)
                if e is not None:
                    self._expr(e, frozenset(held))
            if isinstance(stmt, ast.For):
                self._expr(stmt.target, frozenset(held), store=True)
            self._stmts(stmt.body, frozenset(held))
            self._stmts(stmt.orelse, frozenset(held))
            return held
        if isinstance(stmt, ast.Try):
            h = self._stmts(stmt.body, frozenset(held))
            for hd in stmt.handlers:
                self._stmts(hd.body, frozenset(held))
            self._stmts(stmt.orelse, h)
            # finally runs with the body's exit set in the common case
            end = self._stmts(stmt.finalbody, h)
            return set(end) if stmt.finalbody else set(h)
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._assign(stmt, frozenset(held))
            return held
        if isinstance(stmt, (ast.Return, ast.Raise)):
            if getattr(stmt, "value", None) is not None:
                self._expr(stmt.value, frozenset(held))
            if getattr(stmt, "exc", None) is not None:
                self._expr(stmt.exc, frozenset(held))
            return held
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._expr(t, frozenset(held), store=True)
            return held
        # generic: visit child expressions, nested statements, and
        # structural containers that are neither (match_case,
        # ExceptHandler-likes on future grammars) — their statement
        # bodies still run with the current held set
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, frozenset(held))
            elif isinstance(child, ast.stmt):
                self._stmt(child, set(held))
            else:
                body = getattr(child, "body", None)
                if isinstance(body, list):
                    self._stmts(body, frozenset(held))
                guard = getattr(child, "guard", None)
                if isinstance(guard, ast.expr):
                    self._expr(guard, frozenset(held))
        return held

    def _acquire(self, lid, held, line):
        self.fm.direct_acquires.add(lid)
        self.fm.acquire_sites.append((lid, line))
        for h in held:
            if h != lid:
                self.fm.edges.append(Edge(h, lid, line))

    # ---- assignments & expressions ----
    def _assign(self, stmt, held):
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        value = getattr(stmt, "value", None)
        if value is not None:
            self._expr(value, held)
        for t in targets:
            self._expr(t, held, store=True)
        if isinstance(stmt, ast.AugAssign):
            # x += 1 also READS x; record the read explicitly (the
            # store=True pass above recorded the write)
            self._record_access(stmt.target, "read", held)

    def _record_access(self, node, kind, held):
        # peel subscripts and attribute chains down to the shared base:
        # self.X[k] = v and self.X.field = v both touch X
        target = node
        while True:
            if isinstance(target, ast.Subscript):
                target = target.value
            elif isinstance(target, ast.Attribute) \
                    and not _is_self_attr(target):
                target = target.value
            else:
                break
        attr = self.resolve_attr(target)
        if attr is not None:
            self.fm.accesses.append(Access(
                attr=attr, kind=kind, locks=frozenset(held),
                line=node.lineno, col=node.col_offset,
                in_init=self.in_init, func=self.fm.fi))

    def _expr(self, node, held, store=False):
        if node is None:
            return
        if isinstance(node, (ast.Attribute, ast.Name)):
            self._record_access(node, "write" if store else "read", held)
            if isinstance(node, ast.Attribute):
                self._expr(node.value, held)
            return
        if isinstance(node, ast.Subscript):
            self._record_access(node, "write" if store else "read", held)
            self._expr(node.value, held)
            self._expr(node.slice, held)
            return
        if isinstance(node, (ast.Tuple, ast.List)) and store:
            for e in node.elts:
                self._expr(e, held, store=True)
            return
        if isinstance(node, ast.Call):
            self._call(node, held)
            return
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, held)

    def _expr_children(self, call, held):
        for a in call.args:
            self._expr(a, held)
        for k in call.keywords:
            self._expr(k.value, held)

    def _call(self, node, held):
        f = node.func
        dotted = _dotted(f)
        last = dotted.split(".")[-1] if dotted else ""
        # mutator method on a shared container: self.X.append(v)
        if isinstance(f, ast.Attribute):
            if last in _MUTATORS:
                self._record_access(f.value, "write", held)
            elif last in ("get",) and (node.args or node.keywords):
                # dict-style read access self.X.get(k)
                self._record_access(f.value, "read", held)
            else:
                self._expr(f.value, held)
        # held-lock bookkeeping for inline acquire()/release() used in
        # expression position (rare)
        if isinstance(f, ast.Attribute) and last in ("acquire",):
            lid = self.resolve_lock(f.value)
            if lid is not None:
                self._acquire(lid, held, node.lineno)
        # blocking-call audit: record the site regardless of the held
        # set — a callee's blocking site matters when a CALLER holds a
        # lock across the call (race_rules surfaces those too)
        desc = self._blocking_desc(node, dotted, last, held)
        if desc is not None:
            bc = BlockingCall(desc=desc, locks=frozenset(held),
                              line=node.lineno, col=node.col_offset)
            self.fm.blocking_any.append(bc)
            if held:
                self.fm.blocking.append(bc)
        # calls into same-module code (context flow, transitive lock
        # acquisition, and — while holding — the interprocedural half
        # of the RL102 edge set)
        callee = self._resolve_callee(f)
        if callee is not None:
            self.fm.calls.append((callee.qualname, node.lineno))
            if held:
                self.fm.held_calls.append(
                    (callee.qualname, frozenset(held), node.lineno))
        elif _is_self_attr(f) and self.cm is not None \
                and last not in _MUTATORS and last != "get":
            # a STORED callable (self.on_transition(...)): arbitrary
            # user code — a convoy/deadlock hazard under a lock (the
            # callback may well try to take the same lock)
            bc = BlockingCall(
                desc=f"user callback self.{f.attr}()",
                locks=frozenset(held), line=node.lineno,
                col=node.col_offset)
            self.fm.blocking_any.append(bc)
            if held:
                self.fm.blocking.append(bc)
        self._expr_children(node, held)

    def _resolve_callee(self, f):
        fi = self.fm.fi
        if isinstance(f, ast.Name):
            return self.mm.index._resolve_name(f.id, fi)
        if _is_self_attr(f) and self.cm is not None:
            return self.mm.index.methods.get(
                id(self.cm.node), {}).get(f.attr)
        return None

    def _blocking_desc(self, node, dotted, last, held):
        """A human-readable description when `node` is a blocking call
        (made while holding `held`), else None."""
        nargs = len(node.args)
        kwnames = {k.arg for k in node.keywords}
        if last == "join" and isinstance(node.func, ast.Attribute):
            # thread/process join takes 0 args or timeout=; str.join and
            # os.path.join always take the iterable positionally
            if nargs == 0 and "sep" not in kwnames \
                    and "path" not in dotted:
                return "join()"
        if last == "sleep":
            return f"{dotted or 'sleep'}()"
        if last == "get" and nargs == 0 \
                and not ({"timeout", "block"} & kwnames):
            return "un-timed queue get()"
        if last == "wait" and nargs == 0 and "timeout" not in kwnames:
            lid = self.resolve_lock(node.func.value) \
                if isinstance(node.func, ast.Attribute) else None
            if lid is not None:
                # cv.wait() releases the condition it is called on —
                # only a problem if OTHER locks are held across it
                return ("un-timed wait()"
                        if held - {lid} else None)
            return "un-timed wait()"
        if dotted.startswith("subprocess.") or last in (
                "check_call", "check_output", "communicate"):
            return f"{dotted}()"
        if last == "open" and dotted in ("open", "io.open"):
            return "file open()"
        if last in ("accept", "recv", "recv_bytes", "connect"):
            return f"socket/pipe {last}()"
        if last in ("write_atomic",):
            return f"{dotted}() [fsync'd file write]"
        if last == "print" or dotted == "print":
            return "print()"
        return None

    # ---- RL201: check-then-act ----
    def _maybe_toctou(self, stmt, held):
        """`if <reads shared attr A>: <mutates A>` — record the site;
        race_rules decides whether the held set actually guards A."""
        test_attrs = set()
        for n in ast.walk(stmt.test):
            if isinstance(n, (ast.Attribute, ast.Name)):
                a = self.resolve_attr(n)
                if a is not None:
                    test_attrs.add(a)
        if not test_attrs:
            return
        body_writes = set()
        for s in stmt.body:
            for n in ast.walk(s):
                target = None
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        target = t
                        self._collect_write(target, body_writes)
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                    self._collect_write(n.target, body_writes)
                elif isinstance(n, ast.Delete):
                    for t in n.targets:
                        self._collect_write(t, body_writes)
                elif isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in _MUTATORS:
                    self._collect_write(n.func.value, body_writes)
        hit = test_attrs & body_writes
        if not hit:
            return
        sink = self.cm.toctou if self.cm is not None else self.mm.toctou
        for attr in sorted(hit):
            sink.append(TOCTOU(attr=attr, locks=held, line=stmt.lineno,
                               col=stmt.col_offset, func=self.fm.fi))

    def _collect_write(self, target, out):
        while isinstance(target, ast.Subscript):
            target = target.value
        a = self.resolve_attr(target)
        if a is not None:
            out.add(a)


# --------------------------------------------------------------- builder
class ModuleBuilder:
    """Extracts the ModuleModel from one parsed file."""

    def __init__(self, path, modname, source, tree):
        self.mm = ModuleModel(path, modname, source, tree)

    def build(self):
        mm = self.mm
        for node in ast.walk(mm.index.tree):
            if isinstance(node, ast.ClassDef):
                mm.classes[node.name] = ClassModel(
                    mm.modname, node.name, node, mm.path)
        self._collect_module_level()
        self._scan_functions()
        # index order lists enclosing functions before their closures,
        # so a nested walker can inherit the parent's local-lock table
        # (a Condition bound in the driver, waited on in the workers)
        walkers = {}
        for fi in mm.index.functions:
            w = _FuncWalker(mm, mm.func_model(fi))
            parent = walkers.get(fi.qualname.rsplit(".", 1)[0]) \
                if "." in fi.qualname else None
            if parent is not None:
                w.local_locks.update(parent.local_locks)
            walkers[fi.qualname] = w
            w.walk()
        return mm

    # ---- module-level state ----
    def _collect_module_level(self):
        mm = self.mm
        # module-level locks / safe containers / shared globals
        for stmt in mm.index.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                kind = _ctor_kind(stmt.value)
                if kind in LOCK_TYPES:
                    mm.locks[name] = LockInfo(
                        f"{mm.modname}.{name}", kind, mm.path,
                        stmt.lineno)
                elif kind is not None:
                    mm.safe_globals.add(name)
                elif isinstance(stmt.value, (ast.Dict, ast.List)):
                    # module-level mutable container: shared iff some
                    # function subscript-writes it (_scan_functions)
                    mm.shared_globals.add(name)

    def _global_write(self, target, out):
        while isinstance(target, ast.Subscript):
            target = target.value
            if isinstance(target, ast.Name):
                out.add(target.id)
                return

    # ---- the one per-function structural scan ----
    def _scan_functions(self):
        """ONE same-scope walk per function collecting everything the
        model needs besides lock sets: `global`-declared and
        subscript-mutated globals, `self.X = <ctor>` lock/safe-type
        classifications, thread/executor/signal roots, and the
        assignment-target map `_creation_joined` consults.  (Lock-set
        tracking needs statement ORDER, so it stays a separate
        structured walk in _FuncWalker.)"""
        mm = self.mm
        joined_attrs, declared = self._module_wide_facts()
        mutated = set()
        for fi in mm.index.functions:
            cm = mm.owner_class(fi)
            assign_of = {}      # id(value node) -> first assign target
            with_items = set()  # id(expr) used as a `with` context item
            for node in walk_same_scope(fi.node):
                if isinstance(node, ast.With):
                    for item in node.items:
                        with_items.add(id(item.context_expr))
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        self._global_write(t, mutated)
                    if len(node.targets) == 1:
                        assign_of[id(node.value)] = node.targets[0]
                        t0 = node.targets[0]
                        if _is_self_attr(t0) and cm is not None:
                            kind = _ctor_kind(node.value)
                            if kind in LOCK_TYPES:
                                cm.locks[t0.attr] = LockInfo(
                                    f"{cm.qual}.{t0.attr}", kind,
                                    mm.path, node.lineno)
                            elif kind is not None:
                                cm.safe_attrs.add(t0.attr)
                    continue
                if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    self._global_write(node.target, mutated)
                    continue
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _MUTATORS and \
                        isinstance(node.func.value, ast.Name):
                    mutated.add(node.func.value.id)
                self._maybe_root(fi, cm, node, joined_attrs, assign_of,
                                 with_items)
        mm.shared_globals = (mm.shared_globals & mutated) | {
            n for n in declared if n not in mm.locks
            and n not in mm.safe_globals}

    def _maybe_root(self, fi, cm, node, joined_attrs, assign_of,
                    with_items):
        mm = self.mm
        dotted = _dotted(node.func)
        last = dotted.split(".")[-1] if dotted else ""
        if last in ("Thread", "Process"):
            kw = {k.arg: k.value for k in node.keywords
                  if k.arg is not None}
            self._thread_root(fi, cm, node, kw, joined_attrs,
                              assign_of, shares_memory=last == "Thread")
        elif last == "submit" and node.args:
            tgt = self._resolve_target(node.args[0], fi, cm)
            if tgt is not None:
                self._add_root("executor", tgt, fi, node, daemon=True)
        elif last == "signal" and len(node.args) == 2:
            tgt = self._resolve_target(node.args[1], fi, cm)
            if tgt is not None:
                self._add_root("signal", tgt, fi, node, daemon=True)
        elif last in ("ThreadPoolExecutor", "ProcessPoolExecutor"):
            # `with ThreadPoolExecutor(...) as ex:` shuts down on exit
            shut = id(node) in with_items or self._has_shutdown(cm)
            sink = cm.executors if cm is not None else mm.executors
            sink.append((fi.qualname, node.lineno, shut))
        elif last == "install" and len(node.args) == 1 and \
                isinstance(node.args[0], ast.Name) and \
                node.args[0].id == "self" and cm is not None:
            # a handler object registered process-wide: its public
            # methods run on whatever thread delivers the event
            cm.roots.append(ThreadRoot(
                root_id=f"installed:{cm.qual}",
                kind="installed", target=None, path=mm.path,
                line=node.lineno, daemon=True, joined=True))

    def _thread_root(self, fi, cm, node, kw, joined_attrs, assign_of,
                     shares_memory):
        mm = self.mm
        tgt = None
        if "target" in kw:
            tgt = self._resolve_target(kw["target"], fi, cm)
        daemon = False
        d = kw.get("daemon")
        if isinstance(d, ast.Constant):
            daemon = bool(d.value)
        joined = self._creation_joined(node, joined_attrs, assign_of)
        kind = "thread" if shares_memory else "process"
        sink = cm.thread_creations if cm is not None \
            else mm.thread_creations
        sink.append((node.lineno, daemon, joined,
                     tgt.qualname if tgt is not None else None))
        if tgt is not None:
            self._add_root(kind, tgt, fi, node, daemon=daemon,
                           joined=joined, shares_memory=shares_memory)

    def _add_root(self, kind, tgt, fi, node, daemon=False, joined=False,
                  shares_memory=True):
        mm = self.mm
        tgt_cm = mm.owner_class(tgt)
        root = ThreadRoot(
            root_id=f"{kind}:{mm.modname}.{tgt.qualname}", kind=kind,
            target=tgt, path=mm.path, line=node.lineno, daemon=daemon,
            joined=joined, shares_memory=shares_memory)
        (tgt_cm.roots if tgt_cm is not None else mm.roots).append(root)

    def _resolve_target(self, expr, fi, cm):
        if isinstance(expr, ast.Name):
            return self.mm.index._resolve_name(expr.id, fi)
        if _is_self_attr(expr) and cm is not None:
            return self.mm.index.methods.get(
                id(cm.node), {}).get(expr.attr)
        return None

    def _module_wide_facts(self):
        """One full-tree walk for the facts that are module-wide by
        nature: names a no-arg `.join()` is called on (str/os.path
        joins take args), and `global X` declarations."""
        cached = getattr(self, "_facts", None)
        if cached is not None:
            return cached
        joined, declared = set(), set()
        for node in ast.walk(self.mm.index.tree):
            if isinstance(node, ast.Global):
                declared.update(node.names)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" and not node.args:
                v = node.func.value
                if _is_self_attr(v):
                    joined.add(f"self.{v.attr}")
                elif isinstance(v, ast.Name):
                    joined.add(v.id)
        self._facts = (joined, declared)
        return self._facts

    def _creation_joined(self, node, joined_attrs, assign_of):
        """Was the thread created at `node` ever joined?  True when the
        creation is assigned to a name/attr that is joined somewhere,
        or when the enclosing function loops `for t in threads:
        t.join()` style (any bare-name join)."""
        parent_assign = assign_of.get(id(node))
        if parent_assign is not None:
            if _is_self_attr(parent_assign) and \
                    f"self.{parent_assign.attr}" in joined_attrs:
                return True
            if isinstance(parent_assign, ast.Name) and \
                    parent_assign.id in joined_attrs:
                return True
        # threads collected into a list that is iterated and joined
        return any(not n.startswith("self.") for n in joined_attrs)

    def _has_shutdown(self, cm):
        """Does the class (or the module) ever call `.shutdown()`?
        Cached per scope: one executor-heavy class must not re-walk
        its body per creation site."""
        cache = getattr(self, "_shutdown_cache", None)
        if cache is None:
            cache = self._shutdown_cache = {}
        key = id(cm.node) if cm is not None else 0
        if key in cache:
            return cache[key]
        scope = cm.node if cm is not None else self.mm.index.tree
        found = any(
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "shutdown"
            for node in ast.walk(scope))
        cache[key] = found
        return found


# -------------------------------------------------------- package model
class PackageModel:
    """All modules + the package-wide lock-order graph and root table."""

    def __init__(self):
        self.modules = {}           # modname -> ModuleModel

    def add(self, mm):
        self.modules[mm.modname] = mm

    def finalize(self):
        """Transitive acquire sets, root-context propagation, and the
        global edge list.  Call once after every module is added."""
        for mm in self.modules.values():
            self._close_acquires(mm)
            self._propagate_contexts(mm)

    # ---- transitive lock acquisition + interprocedural edges ----
    def _close_acquires(self, mm):
        funcs = {fm.fi.qualname: fm for fm in mm.all_funcs()}
        # fixpoint over same-module calls (bounded: lock set is small)
        for fm in funcs.values():
            fm.all_acquires = set(fm.direct_acquires)
            fm.all_blocking = list(fm.blocking_any)
        changed = True
        guard = 0
        while changed and guard < 20:
            changed = False
            guard += 1
            for fm in funcs.values():
                for qn, _line in fm.calls:
                    callee = funcs.get(qn)
                    if callee is None:
                        continue
                    before = len(fm.all_acquires)
                    fm.all_acquires |= callee.all_acquires
                    changed |= len(fm.all_acquires) != before
                    for bc in callee.all_blocking:
                        if bc not in fm.all_blocking:
                            fm.all_blocking.append(bc)
                            changed = True
        # a call made while holding H reaches every lock the callee
        # (transitively) acquires (RL102 edges) and every blocking site
        # inside it (RL103)
        for fm in funcs.values():
            for qn, held, line in fm.held_calls:
                callee = funcs.get(qn)
                if callee is None:
                    continue
                for lid in sorted(callee.all_acquires):
                    for h in held:
                        if h != lid:
                            fm.edges.append(Edge(h, lid, line))
                for bc in callee.all_blocking:
                    hit = BlockingCall(
                        desc=f"{bc.desc} [via {qn.split('.')[-1]}()]",
                        locks=held, line=bc.line, col=bc.col)
                    fm.blocking.append(hit)

    # ---- root contexts ----
    def _propagate_contexts(self, mm):
        for cm in mm.classes.values():
            self._class_contexts(mm, cm)
        # module-level functions: roots vs main
        root_targets = {r.target.qualname: r for r in mm.roots
                        if r.target is not None}
        for fm in mm.funcs.values():
            qn = fm.fi.qualname
            if qn in root_targets:
                fm.contexts.add(root_targets[qn].root_id)
                fm.is_root_target = True
            elif "." not in qn:
                fm.contexts.add(MAIN)
        self._flow_contexts(mm, mm.funcs)

    def _class_contexts(self, mm, cm):
        root_targets = {}
        installed = None
        for r in cm.roots:
            if r.target is not None:
                root_targets.setdefault(r.target.qualname, []).append(r)
            elif r.kind == "installed":
                installed = r
        for fm in cm.funcs.values():
            qn = fm.fi.qualname
            name = qn.split(".")[-1]
            if qn in root_targets:
                for r in root_targets[qn]:
                    fm.contexts.add(r.root_id)
                fm.is_root_target = True
            # nested closures inherit from their enclosing function in
            # the flow pass; direct methods default to the main thread
            elif "." in qn and qn.count(".") == 1:
                fm.contexts.add(MAIN)
                if installed is not None and not name.startswith("_") \
                        and name != "__init__":
                    fm.contexts.add(installed.root_id)
        self._flow_contexts(mm, cm.funcs)

    def _flow_contexts(self, mm, funcs):
        """Callees (and nested closures) run in their callers'
        contexts."""
        changed = True
        guard = 0
        while changed and guard < 20:
            changed = False
            guard += 1
            for fm in funcs.values():
                # nested closure: runs in the enclosing fn's contexts —
                # unless it is itself a thread-root target, in which
                # case it runs ONLY where its thread does
                if "." in fm.fi.qualname and not fm.is_root_target:
                    parent = funcs.get(
                        fm.fi.qualname.rsplit(".", 1)[0])
                    if parent is not None:
                        before = len(fm.contexts)
                        fm.contexts |= parent.contexts
                        changed |= len(fm.contexts) != before
                # fm.calls already resolves self.m() from closures too
                # (owner class recovered via the qualname prefix)
                for qn, _line in fm.calls:
                    cfm = funcs.get(qn)
                    if cfm is None or cfm.is_root_target:
                        continue
                    before = len(cfm.contexts)
                    cfm.contexts |= fm.contexts
                    changed |= len(cfm.contexts) != before

    # ---- the global lock-order graph ----
    def lock_graph(self):
        """{(held, acquired): [(path, line), ...]} over every module."""
        graph = {}
        for mm in self.modules.values():
            for fm in mm.all_funcs():
                for e in fm.edges:
                    graph.setdefault(e.key(), []).append(
                        (mm.path, e.line))
        return graph

    def lock_sites(self):
        """{lock_id: (path, line)} creation sites, package-wide."""
        out = {}
        for mm in self.modules.values():
            for info in mm.locks.values():
                out[info.lock_id] = (info.path, info.line)
            for cm in mm.classes.values():
                for info in cm.locks.values():
                    out[info.lock_id] = (info.path, info.line)
        return out


def find_cycles(graph_keys):
    """Cycles in the directed graph given as an iterable of (a, b)
    edges.  Returns a sorted list of cycles, each a tuple of nodes in
    a canonical rotation (smallest node first)."""
    adj = {}
    for a, b in graph_keys:
        adj.setdefault(a, set()).add(b)
    cycles = set()

    def dfs(start, node, path, on_path):
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                cyc = tuple(path)
                k = cyc.index(min(cyc))
                cycles.add(cyc[k:] + cyc[:k])
            elif nxt not in on_path and nxt > start:
                # only explore nodes > start: each cycle is found from
                # its smallest node exactly once
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for start in sorted(adj):
        dfs(start, start, [start], {start})
    return sorted(cycles)
