"""numlint NL1xx/NL2xx/NL3xx: numerics & precision-flow audit of jaxprs.

tracelint asks "will it trace", shardlint asks "will it scale" — numlint
asks "will the numbers still be right": a judgment pass over the dtype
provenance :mod:`dtype_flow` extracts from a traced program.

- **NL1xx precision loss** — serial reductions and dot contractions
  accumulating in a narrow dtype without a widening cast (NL101),
  f32→bf16→f32 double-rounding round trips whose wide original was
  still live (NL102), and optimizer-plane state (params / moments)
  stored narrow without the explicit ``moment_dtype`` opt-in (NL103 —
  the invariant PR 10 pinned dynamically via SL303=0, proven statically
  here on every audited program).
- **NL2xx stability** — exp/log/div/rsqrt on a narrow dtype with no
  max-subtraction or eps-guard upstream (NL201), and scan carries
  narrower than the body math that updates them (NL202).
- **NL3xx quantization readiness** — int8/fp8 codes consumed with no
  adjacent scale (NL301) and dequant→requant chains that should fuse
  (NL302).  Written against HYPOTHETICAL quantized pools: the rules
  gate ROADMAP item 2's KV-quantization PR before it lands, the same
  way shardlint audits CPU traces against a hypothetical mesh.

Findings resolve to real file:line through eqn source_info, so the
ordinary ``# tracelint: disable=NL101`` (and the NL-scoped
``# numlint:`` alias) suppressions apply.  Thresholds live on
:class:`NumConfig`; deliberate narrow accumulation registers once via
``core.dispatch.allow_narrow_accum`` (the same promotion-metadata shape
TL401's wide-dtype allowlist uses).

Module-level imports are stdlib-only (jax arrives via the jaxpr).
"""
from __future__ import annotations

import fnmatch
from dataclasses import dataclass

from paddle_tpu.analysis.dtype_flow import (DtypeFlow, NARROW_FLOATS,
                                            QUANT_DTYPES)
from paddle_tpu.analysis.shard_rules import (_aval_sig, _eqn_site,
                                             _mk_finding,
                                             apply_suppressions)

__all__ = ["NumConfig", "check_numerics"]


@dataclass(frozen=True)
class NumConfig:
    """Thresholds for the NL rule families (one knob set shared by the
    CLI, the to_static(check=True) hook, and the bench lane).  The
    defaults are production-scale; the CLI scales them down so the same
    defect classes fire on the tiny CI configs (shardlint's pattern)."""

    # NL101: smallest reduction depth (addends per output element)
    # worth flagging — bf16's 8-bit mantissa absorbs small addends once
    # the running total is ~256x larger, so short reductions are safe
    reduce_min_elems: int = 1024
    # NL103: fnmatch patterns of opt-state names whose narrow storage
    # is an explicit, tested opt-in (Adam/AdamW moment_dtype)
    moment_optin: tuple = ()
    # NL201: largest additive literal that counts as an eps-guard
    eps_max: float = 1e-2
    # NL302: flag only chains whose intermediate float has no other
    # consumer (True) or every chain (False)
    requant_fused_only: bool = True


def _detail_site(eqn):
    path, line = _eqn_site(eqn)
    return f" at {path}:{line}" if path else ""


def check_numerics(closed_jaxpr, where="<traced program>", inputs=None,
                   config=None, suppress=True):
    """Run the NL rule families over one traced program.

    - `inputs`: [InputInfo] aligned with the jaxpr invars (the NL103
      master-state pass reads kinds/names/dtypes from it; pass the
      second element of :meth:`StaticFunction.traced_program`).
    - `suppress`: apply per-line ``# tracelint: disable=NLxxx`` /
      ``# numlint: disable=...`` comments at each finding's resolved
      source site.

    Returns ``[Finding]`` sorted by (path, line, code).
    """
    config = config or NumConfig()
    flow = DtypeFlow(closed_jaxpr, inputs=inputs, eps_max=config.eps_max)
    findings = []
    findings += _nl101(flow, config, where)
    findings += _nl102(flow, where)
    findings += _nl103(inputs, config, where)
    findings += _nl201(flow, where)
    findings += _nl202(flow, where)
    findings += _nl301(flow, where)
    findings += _nl302(flow, config, where)
    if suppress:
        findings = apply_suppressions(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


# ------------------------------------------------------------------ NL101
def _nl101(flow, config, where):
    from paddle_tpu.core import dispatch
    allowed = dispatch.narrow_accum_allowed_ops()
    findings, seen = [], set()
    for ev in flow.result.reductions:
        if ev.widened or ev.prim in allowed:
            continue
        if ev.operand_prov.dtype not in NARROW_FLOATS:
            continue
        if ev.out_dtype not in NARROW_FLOATS:
            continue
        if ev.reduce_elems < config.reduce_min_elems:
            continue
        out = ev.eqn.outvars[0]
        key = (ev.prim, _aval_sig(out), _eqn_site(ev.eqn))
        if key in seen:
            continue
        seen.add(key)
        findings.append(_mk_finding(
            "NL101",
            f"`{ev.prim}` -> {_aval_sig(out)} "
            f"({ev.reduce_elems} addends in {ev.out_dtype})",
            where, eqn=ev.eqn,
            sig=f"narrow-accum {ev.prim} {_aval_sig(out)} "
                f"k={ev.reduce_elems}"))
    return findings


# ------------------------------------------------------------------ NL102
def _nl102(flow, where):
    findings, seen = [], set()
    for ev in flow.result.round_trips:
        if not ev.wide_live:
            continue      # residency round trip: the narrow copy is
            # the only survivor, re-widening it is the point
        if ev.wide_root_is_input:
            continue      # cast chains rooted at a program input are
            # shardlint SL303's finding (docs/shardlint.md: one
            # fingerprint owns a given chain)
        key = _eqn_site(ev.widen_eqn)
        if key in seen:
            continue
        seen.add(key)
        findings.append(_mk_finding(
            "NL102",
            f"{ev.wide_dtype} -> {ev.narrow_dtype} -> {ev.wide_dtype}"
            f"{_detail_site(ev.narrow_eqn)}",
            where, eqn=ev.widen_eqn,
            sig=f"roundtrip {ev.wide_dtype}->{ev.narrow_dtype}"))
    return findings


# ------------------------------------------------------------------ NL103
def _nl103(inputs, config, where):
    findings = []
    for info in inputs or ():
        dt = str(info.dtype)
        narrow = dt in NARROW_FLOATS or dt in QUANT_DTYPES
        if not narrow:
            continue
        if info.kind == "opt_state":
            if any(fnmatch.fnmatch(info.name, pat)
                   for pat in config.moment_optin):
                continue
            findings.append(_mk_finding(
                "NL103",
                f"moment `{info.name}` ({dt}{list(info.shape)})",
                where, sig=f"narrow-moment {info.name}"))
        elif info.kind == "param":
            findings.append(_mk_finding(
                "NL103",
                f"param `{info.name}` ({dt}{list(info.shape)}) has no "
                f"f32 master copy",
                where, sig=f"narrow-param {info.name}"))
    return findings


# ------------------------------------------------------------------ NL201
def _nl201(flow, where):
    findings, seen = [], set()
    for ev in flow.result.transcendentals:
        if ev.stabilized:
            continue
        key = (ev.prim, _eqn_site(ev.eqn))
        if key in seen:
            continue
        seen.add(key)
        findings.append(_mk_finding(
            "NL201",
            f"{ev.prim}({ev.operand_prov.dtype})",
            where, eqn=ev.eqn,
            sig=f"unstabilized {ev.prim} {ev.operand_prov.dtype}"))
    return findings


# ------------------------------------------------------------------ NL202
def _nl202(flow, where):
    findings = []
    for ev in flow.result.scan_carries:
        findings.append(_mk_finding(
            "NL202",
            f"slot {ev.slot} ({ev.carry_dtype}) vs {ev.body_dtype} "
            f"body math",
            where, eqn=ev.eqn,
            sig=f"narrow-carry slot{ev.slot} {ev.carry_dtype}"))
    return findings


# ------------------------------------------------------------------ NL301
def _nl301(flow, where):
    findings, seen = [], set()
    for ev in flow.result.quant_uses:
        if ev.has_scale_operand:
            continue
        key = (ev.prim, ev.operand_dtype, _eqn_site(ev.eqn))
        if key in seen:
            continue
        seen.add(key)
        kind = "raw codes" if ev.raw else "un-descaled dequant"
        findings.append(_mk_finding(
            "NL301",
            f"({ev.operand_dtype} {kind}) in `{ev.prim}`",
            where, eqn=ev.eqn,
            sig=f"scale-free {ev.prim} {ev.operand_dtype}"))
    return findings


# ------------------------------------------------------------------ NL302
def _nl302(flow, config, where):
    findings, seen = [], set()
    for ev in flow.result.requants:
        if config.requant_fused_only and ev.intermediate_other_uses > 0:
            continue
        key = _eqn_site(ev.eqn)
        if key in seen:
            continue
        seen.add(key)
        out_dt = str(ev.eqn.params.get("new_dtype", ""))
        findings.append(_mk_finding(
            "NL302",
            f"-> {out_dt} (intermediate float has "
            f"{ev.intermediate_other_uses} other consumer(s))",
            where, eqn=ev.eqn,
            sig=f"requant {out_dt}"))
    return findings
