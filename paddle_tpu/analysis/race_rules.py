"""racelint rules: the RLxxx family over :mod:`lock_model`.

Whole-package pass (cross-module lock-order graph, per-class shared
state).  Findings resolve to real file:line sites and honor the same
`# tracelint: disable=RLxxx` / `# racelint: disable=...` suppression
comments the other analyzers use.  Like tracelint, the pass
over-approximates on purpose: a finding is a *hazard*, and the
checked-in baseline absorbs the reviewed backlog so `--check` fails
only on regressions.

Rule summary (catalogue text lives in :mod:`rules`):

- **RL101** attribute shared across ≥2 thread roots with inconsistent
  (or empty) lock sets.
- **RL102** lock-order inversion cycles in the package-wide
  acquired-while-holding graph.
- **RL103** blocking calls (join, un-timed ``queue.get``, sleep,
  file/subprocess IO) while holding a lock.
- **RL104** signal handlers that do more than set a flag.
- **RL105** thread/executor lifecycle leaks.
- **RL201** check-then-act TOCTOU on a shared container outside its
  guarding lock.
"""
from __future__ import annotations

import ast
import os

from paddle_tpu.analysis import lock_model
from paddle_tpu.analysis.lock_model import PackageModel
from paddle_tpu.analysis.rules import message_for
from paddle_tpu.analysis.visitor import (Finding, _dotted, iter_py_files,
                                         parse_suppressions, rel_path)

# attribute-name suffixes whose unlocked sharing is overwhelmingly
# benign telemetry (monotonic counters read for reporting only) —
# demoting them keeps RL101 focused; a counter that must be exact
# should be an observability Counter (which locks) anyway
_COUNTERISH = ("_count", "_total", "_seq", "_steps", "count")


def modname_for(path, base=None):
    rel = rel_path(path, base)
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def build_package_model(paths, base=None):
    """Parse every .py under `paths` into one PackageModel.  Returns
    (model, {path: (suppressions, skip_file)}, [parse-error Finding])."""
    pm = PackageModel()
    sups = {}
    errors = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        except OSError:
            continue
        rel = rel_path(path, base)
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            errors.append(Finding(
                path=rel, line=e.lineno or 1, col=e.offset or 0,
                code="RL000", message=f"syntax error: {e.msg}"))
            continue
        sup, skip = parse_suppressions(source)
        sups[rel] = (sup, skip, source.splitlines())
        mm = lock_model.ModuleBuilder(
            path=rel, modname=modname_for(path, base), source=source,
            tree=tree).build()
        pm.add(mm)
    pm.finalize()
    return pm, sups, errors


def _finding(path, line, col, code, detail):
    return Finding(path=path, line=line, col=col, code=code,
                   message=message_for(code, detail=detail))


def _short(attr_or_lock):
    """Trailing two segments — enough to identify `Class.attr` in a
    message without the full module path."""
    return ".".join(attr_or_lock.split(".")[-2:])


# ------------------------------------------------------------- RL101
def _check_shared_state(pm):
    findings = []
    for mm in pm.modules.values():
        # class attributes group per class, but MODULE GLOBALS must
        # aggregate across every function in the module — a global
        # written by a class method and read by a module function is
        # still one shared object (scope-splitting it would make that
        # race undetectable)
        by_attr = {}
        for fm in mm.all_funcs():
            ctxs = {c for c in fm.contexts
                    if not c.startswith("process:")}
            if not ctxs:
                continue
            for acc in fm.accesses:
                by_attr.setdefault(acc.attr, []).append((acc, ctxs))
        for attr, accs in sorted(by_attr.items()):
            f = _rl101_for_attr(mm, attr, accs)
            if f is not None:
                findings.append(f)
    return findings


def _rl101_for_attr(mm, attr, accs):
    live = [(a, ctxs) for a, ctxs in accs if not a.in_init]
    if not live:
        return None
    contexts = set().union(*(ctxs for _a, ctxs in live))
    if len(contexts) < 2:
        return None
    writes = [a for a, _ in live if a.kind == "write"]
    if not writes:
        return None    # init-published, read-only after: happens-before
    # single-writer-context attributes written only by one root and
    # merely read elsewhere still race (torn/stale reads), but the
    # high-signal case is multi-context writes or write+read overlap
    lock_sets = [a.locks for a, _ in live]
    common = frozenset.intersection(*lock_sets)
    if common:
        return None    # one lock consistently guards every access
    name = attr.split(".")[-1]
    if name.endswith(_COUNTERISH):
        return None
    w = min(writes, key=lambda a: a.line)
    guarded = sum(1 for s in lock_sets if s)
    detail = (f"`{_short(attr)}` ({len(live)} access sites, "
              f"{guarded} locked, across "
              f"{len(contexts)} thread roots)")
    return _finding(mm.path, w.line, w.col, "RL101", detail)


# ------------------------------------------------------------- RL102
def _check_lock_order(pm):
    findings = []
    graph = pm.lock_graph()
    cycles = lock_model.find_cycles(graph.keys())
    for cyc in cycles:
        # report at the first edge's first site, naming the whole cycle
        edges = list(zip(cyc, cyc[1:] + cyc[:1]))
        path, line = sorted(graph[edges[0]])[0]
        order = " -> ".join(_short(n) for n in cyc + (cyc[0],))
        sites = "; ".join(
            f"{_short(a)}->{_short(b)} at "
            f"{sorted(graph[(a, b)])[0][0]}:{sorted(graph[(a, b)])[0][1]}"
            for a, b in edges)
        findings.append(_finding(
            path, line, 0, "RL102", f"{order} ({sites})"))
    return findings


# ------------------------------------------------------------- RL103
def _check_blocking(pm):
    findings = []
    for mm in pm.modules.values():
        seen = set()        # one finding per blocking SITE
        for fm in mm.all_funcs():
            for b in fm.blocking:
                if (b.line, b.col) in seen:
                    continue
                seen.add((b.line, b.col))
                locks = ", ".join(_short(x) for x in sorted(b.locks))
                findings.append(_finding(
                    mm.path, b.line, b.col, "RL103",
                    f"{b.desc} (holding {locks})"))
    return findings


# ------------------------------------------------------------- RL104
_IO_NAMES = {"print", "open", "write", "flush", "dump", "dumps"}


def _check_signal_handlers(pm):
    findings = []
    for mm in pm.modules.values():
        handlers = []
        for cm in mm.classes.values():
            for fm in cm.funcs.values():
                if any(c.startswith("signal:") for c in fm.contexts):
                    handlers.append((mm, fm))
        for fm in mm.funcs.values():
            if any(c.startswith("signal:") for c in fm.contexts):
                handlers.append((mm, fm))
        for mm2, fm in handlers:
            findings.extend(_rl104_for_handler(mm2, fm))
    return findings


def _rl104_for_handler(mm, fm):
    out = []
    qn = fm.fi.qualname
    # lock acquisition anywhere in the handler's (transitive) reach
    for lid, line in fm.acquire_sites:
        out.append(_finding(
            mm.path, line, 0, "RL104",
            f"`{qn}` acquires {_short(lid)}"))
    if fm.all_acquires - fm.direct_acquires:
        locks = ", ".join(sorted(_short(x) for x in
                                 fm.all_acquires - fm.direct_acquires))
        out.append(_finding(
            mm.path, fm.fi.node.lineno, fm.fi.node.col_offset, "RL104",
            f"`{qn}` reaches lock acquisition ({locks}) via calls"))
    # IO / allocation in the handler body itself
    for node in ast.walk(fm.fi.node):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        last = dotted.split(".")[-1] if dotted else ""
        if last in _IO_NAMES:
            out.append(_finding(
                mm.path, node.lineno, node.col_offset, "RL104",
                f"`{qn}` performs IO ({last})"))
    return out


# ------------------------------------------------------------- RL105
def _check_lifecycle(pm):
    findings = []
    for mm in pm.modules.values():
        creation_scopes = [(cm, cm.thread_creations, cm.executors)
                           for cm in mm.classes.values()]
        creation_scopes.append((None, mm.thread_creations, mm.executors))
        for _cm, threads, executors in creation_scopes:
            for line, daemon, joined, target in threads:
                if daemon or joined:
                    continue
                tgt = f" (target {target.split('.')[-1]})" if target \
                    else ""
                findings.append(_finding(
                    mm.path, line, 0, "RL105",
                    f"non-daemon thread{tgt} is never joined — blocks "
                    f"interpreter exit"))
            for qn, line, has_shutdown in executors:
                if has_shutdown:
                    continue
                findings.append(_finding(
                    mm.path, line, 0, "RL105",
                    f"executor created in `{qn}` is never shut down"))
    return findings


# ------------------------------------------------------------- RL201
def _check_toctou(pm):
    findings = []
    for mm in pm.modules.values():
        scopes = [(cm.toctou, cm.funcs) for cm in mm.classes.values()]
        scopes.append((mm.toctou, mm.funcs))
        for toctous, funcs in scopes:
            # which locks guard each attr elsewhere in the scope?
            guards = {}
            for fm in funcs.values():
                for acc in fm.accesses:
                    if acc.locks:
                        guards.setdefault(acc.attr, set()).update(
                            acc.locks)
            for t in toctous:
                attr_guards = guards.get(t.attr, set())
                if not attr_guards:
                    continue    # no lock discipline at all -> RL101's job
                if t.locks & attr_guards:
                    continue    # the guarding lock IS held here
                locks = ", ".join(sorted(_short(x)
                                         for x in attr_guards))
                findings.append(_finding(
                    mm.path, t.line, t.col, "RL201",
                    f"`{_short(t.attr)}` (guarded by {locks} "
                    f"elsewhere)"))
    return findings


# -------------------------------------------------------------- driver
ALL_CHECKS = (_check_shared_state, _check_lock_order, _check_blocking,
              _check_signal_handlers, _check_lifecycle, _check_toctou)


def lint_package(paths, base=None):
    """The racelint entry: AST-model every file under `paths`, run the
    RL rules package-wide, apply suppressions.  Returns [Finding]."""
    pm, sups, findings = build_package_model(paths, base=base)
    for check in ALL_CHECKS:
        findings.extend(check(pm))
    out = []
    for f in findings:
        entry = sups.get(f.path)
        if entry is not None:
            sup, skip, lines = entry
            if skip:
                continue
            codes = sup.get(f.line, ())
            if "ALL" in codes or "ALL:RL" in codes or f.code in codes:
                continue
            if 1 <= f.line <= len(lines):
                f.source_line = lines[f.line - 1].strip()
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out


def static_lock_order(paths, base=None):
    """(edges, lock_sites) for the lock-order sanitizer's cross-check:
    edges is {(held_id, acquired_id): [(path, line), ...]},
    lock_sites {lock_id: (path, line)} — creation sites are how the
    runtime tracer maps live locks back to static identities."""
    pm, _sups, _errs = build_package_model(paths, base=base)
    return pm.lock_graph(), pm.lock_sites()


def bench_report(paths=None, base=None):
    """The bench.py lane: finding count + per-rule breakdown, so every
    BENCH report records the concurrency-audit picture alongside the
    shardlint cost numbers."""
    import time
    t0 = time.time()
    if paths is None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        paths = [os.path.join(repo, "paddle_tpu")]
        base = repo
    findings = lint_package(paths, base=base)
    breakdown = {}
    for f in findings:
        breakdown[f.code] = breakdown.get(f.code, 0) + 1
    return {
        "racelint_finding_count": len(findings),
        "racelint_rule_breakdown": dict(sorted(breakdown.items())),
        "racelint_elapsed_s": round(time.time() - t0, 2),
    }
