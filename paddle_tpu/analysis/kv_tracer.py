"""Runtime coordination-KV event tracer — protolint's dynamic half.

The static pass (:mod:`kv_model` + :mod:`proto_rules`) proves
properties of the key patterns the package *constructs*; what it
cannot see is the ACTUAL per-process event stream a live run produces
— the order one process sets, consumes and deletes each concrete key,
including runs where a peer is SIGKILLed mid-protocol.  The tracer
closes that gap the way :mod:`lock_tracer` does for lock order:

- :class:`KVEventTracer` monkey-patches the KV client surface
  (``fleet.LocalKVClient``'s methods) for its ``with`` scope, so every
  in-process rank-per-thread fleet the tests build is recorded with
  zero test changes.
- :class:`TracedKVClient` wraps an arbitrary client object (the real
  jax.distributed coordination client in a spawned worker process);
  :func:`arm_from_env` installs it behind ``fleet._client`` /
  ``collective._coord_client`` when ``PTPU_KV_TRACE_DIR`` is set, so
  the multiprocess chaos workers append their streams as JSONL files
  the parent test collects after the kill.
- Every event is identified by :func:`kv_model.normalize_concrete_key`
  — the same construction-site pattern identity the static model keys
  on, which is what makes the static/dynamic cross-check possible.

Verdicts:

- :func:`lifecycle_violations` — per-process streams replayed against
  the key-lifecycle rules: a successful get AFTER this process
  deleted the key (no re-set in between), and a DOUBLE-CONSUME on an
  exactly-once lane (two gets, no intervening set) — the dynamic
  PL102 evidence.  Exactly-once lanes come from the static model's
  consume-then-delete idiom (:func:`consume_once_canons`), or, with
  no model, from the stream itself (a lane this run get-then-deleted
  is a consume lane).
- :func:`check_static` — both conformance directions: observed SET
  patterns the model does not contain (unmodeled protocol surface),
  plus the lifecycle violations above.
- :func:`residual_keys` — the end-of-test "nothing left in the
  store" assertion the multiprocess tests use: every surviving
  ``ptpu/`` key except the reviewed persistent set is a leak.

Event files are append-mode, one JSON object per line, flushed per
event — a SIGKILL loses at most the in-flight line, and the parent's
reader skips torn trailing lines, so kill-chaos runs stay analyzable.
"""
from __future__ import annotations

import json
import os
import threading

from paddle_tpu.analysis.kv_model import (canon,
                                          normalize_concrete_key,
                                          patterns_compatible)

__all__ = ["KVEventTracer", "TracedKVClient", "active_tracer",
           "arm_from_env", "lifecycle_violations", "consume_once_canons",
           "check_static", "residual_keys"]

_active = None

# (method name, event op, is-prefix op) — the sanctioned client
# surface; everything else forwards untraced
_METHODS = (
    ("key_value_set", "set", False),
    ("key_value_set_bytes", "set", False),
    ("blocking_key_value_get", "get", False),
    ("blocking_key_value_get_bytes", "get", False),
    ("key_value_dir_get", "dir", True),
    ("key_value_dir_get_bytes", "dir", True),
    ("key_value_delete", "delete", False),
)

# keys that are DESIGNED to outlive a run (reviewed in
# tools/protolint_baseline.json) — residual_keys ignores them
PERSISTENT_KEYS = ("ptpu/launch/current",)


def active_tracer():
    return _active


class _Sink:
    """Append-mode JSONL event sink, flushed per line (kill-safe)."""

    def __init__(self, path):
        self._fh = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    def write(self, event):
        line = json.dumps(event, sort_keys=True)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self):
        try:
            self._fh.close()
        except Exception:
            pass


class _Recorder:
    """Shared event log: in-memory list plus optional JSONL sink."""

    def __init__(self, sink=None, pid=None):
        self.events = []
        self._lock = threading.Lock()
        self._sink = sink
        self._pid = pid if pid is not None else os.getpid()
        self._n = 0

    def record(self, op, key):
        ev = {"op": op, "key": str(key), "pid": self._pid,
              "i": self._n}
        with self._lock:
            ev["i"] = self._n
            self._n += 1
            self.events.append(ev)
        if self._sink is not None:
            self._sink.write(ev)


class TracedKVClient:
    """Proxy over a coordination-KV client: forwards everything,
    records each SUCCESSFUL sanctioned-surface call (timeouts and
    errors raise through unrecorded — a failed get consumed
    nothing)."""

    def __init__(self, client, recorder):
        self._client = client
        self._recorder = recorder

    def __getattr__(self, name):
        return getattr(self._client, name)


def _traced_method(name, op):
    def method(self, *args, **kwargs):
        out = getattr(self._client, name)(*args, **kwargs)
        key = args[0] if args else kwargs.get(
            "key", kwargs.get("prefix", ""))
        self._recorder.record(op, key)
        return out
    method.__name__ = name
    return method


for _name, _op, _ in _METHODS:
    setattr(TracedKVClient, _name, _traced_method(_name, _op))


class KVEventTracer:
    """Context manager recording every LocalKVClient operation in
    this process (class-level patch: all instances, no test
    changes).  `trace_dir` adds the kill-safe JSONL sink the
    multiprocess workers use."""

    def __init__(self, trace_dir=None, tag=""):
        sink = None
        if trace_dir:
            os.makedirs(trace_dir, exist_ok=True)
            suffix = f"-{tag}" if tag else ""
            sink = _Sink(os.path.join(
                trace_dir, f"kv-{os.getpid()}{suffix}.jsonl"))
        self._sink = sink
        self.recorder = _Recorder(sink=sink)
        self._orig = {}

    @property
    def events(self):
        return list(self.recorder.events)

    def __enter__(self):
        global _active
        if _active is not None:
            raise RuntimeError("a KVEventTracer is already active "
                               "(nesting tracers is not supported)")
        from paddle_tpu.resilience import fleet

        cls = fleet.LocalKVClient
        for name, op, _ in _METHODS:
            orig = getattr(cls, name)
            self._orig[name] = orig

            def patched(inst, *args, _orig=orig, _op=op, **kwargs):
                out = _orig(inst, *args, **kwargs)
                tracer = _active
                if tracer is not None:
                    key = args[0] if args else kwargs.get(
                        "key", kwargs.get("prefix", ""))
                    tracer.recorder.record(_op, key)
                return out

            patched.__name__ = name
            setattr(cls, name, patched)
        _active = self
        return self

    def __exit__(self, *exc):
        global _active
        from paddle_tpu.resilience import fleet

        for name, orig in self._orig.items():
            setattr(fleet.LocalKVClient, name, orig)
        _active = None
        if self._sink is not None:
            self._sink.close()
        return False

    # ---- verdicts ----
    def violations(self, model=None):
        return lifecycle_violations(self.events, model=model)

    def check_static(self, model):
        return check_static(model, self.events)

    def snapshot(self, model=None):
        evs = self.events
        ops = {}
        for ev in evs:
            ops[ev["op"]] = ops.get(ev["op"], 0) + 1
        return {
            "events": len(evs),
            "ops": dict(sorted(ops.items())),
            "violations": lifecycle_violations(evs, model=model),
        }


def arm_from_env():
    """Worker-process arming: when ``PTPU_KV_TRACE_DIR`` is set,
    wrap the real coordination client behind ``fleet._client`` and
    ``collective._coord_client`` in a recording proxy whose JSONL
    stream lands in that directory.  No-op (returns None) when the
    env var is absent, so worker entry points call this
    unconditionally."""
    trace_dir = os.environ.get("PTPU_KV_TRACE_DIR")
    if not trace_dir:
        return None
    os.makedirs(trace_dir, exist_ok=True)
    sink = _Sink(os.path.join(trace_dir,
                              f"kv-{os.getpid()}.jsonl"))
    recorder = _Recorder(sink=sink)

    from paddle_tpu.distributed import collective
    from paddle_tpu.resilience import fleet

    def wrapping(orig):
        def wrapped(*args, **kwargs):
            client = orig(*args, **kwargs)
            if client is None or isinstance(client, TracedKVClient):
                return client
            return TracedKVClient(client, recorder)
        return wrapped

    fleet._client = wrapping(fleet._client)
    collective._coord_client = wrapping(collective._coord_client)
    return recorder


# ----------------------------------------------------------- verdicts
def consume_once_canons(model):
    """Canons the static model consumes with the get-then-delete
    idiom (some function gets the key, then deletes it): the
    exactly-once lanes double-consume applies to."""
    out = set()
    for f in model.funcs:
        gets = {}
        for item in f.items:
            if item[0] != "op":
                continue
            op = item[1]
            if op.opaque:
                continue
            if op.kind in ("get", "get_raw"):
                gets.setdefault(op.canon, op.line)
            elif op.kind == "delete" and not op.shim:
                if op.canon in gets and op.line > gets[op.canon]:
                    out.add(op.canon)
    return out


def _covers_key(deleted, key):
    d = deleted.rstrip("/")
    return key == d or key.startswith(d + "/")


def lifecycle_violations(events, model=None):
    """Replay per-process event streams against the lifecycle rules.

    Returns a sorted list of violation strings (empty == clean):

    - ``get-after-delete``: this process read a key after deleting it
      (or a covering prefix) with no re-set in between — it consumed
      a payload the protocol already reclaimed.
    - ``double-consume``: two successful gets of the same concrete
      key on an exactly-once lane with no intervening set — the
      SIGSTOP-resume / retry double-delivery PL102 polices.
    """
    if model is not None:
        consume = consume_once_canons(model)
    else:
        consume = None      # derive from each stream below
    streams = {}
    for ev in events:
        streams.setdefault(ev.get("pid", 0), []).append(ev)
    out = []
    for pid, evs in sorted(streams.items()):
        evs = sorted(evs, key=lambda e: e.get("i", 0))
        lanes = consume
        if lanes is None:
            # a key this run got and then deleted BY EXACT NAME is a
            # consume-once lane; prefix reaps (the two-rounds-behind
            # sweep) deliberately do not qualify — keys under them
            # are broadcast-read
            lanes = set()
            got = set()
            for ev in evs:
                if ev["op"] == "get":
                    got.add(ev["key"])
                elif ev["op"] == "delete" and ev["key"] in got:
                    lanes.add(canon(normalize_concrete_key(
                        ev["key"])))
        deleted = set()     # concrete keys this process reclaimed
        consumed = set()    # concrete keys this process already got
        for ev in evs:
            op, key = ev["op"], ev["key"]
            if op == "set":
                deleted.discard(key)
                consumed.discard(key)
            elif op == "delete":
                for k in list(consumed):
                    if _covers_key(key, k):
                        consumed.discard(k)
                deleted.add(key)
            elif op == "get":
                hit = [d for d in deleted if _covers_key(d, key)]
                if hit:
                    out.append(
                        f"get-after-delete pid={pid}: '{key}' read "
                        f"after this process deleted '{hit[0]}'")
                pat = canon(normalize_concrete_key(key))
                if key in consumed and pat in lanes:
                    out.append(
                        f"double-consume pid={pid}: exactly-once key "
                        f"'{key}' read twice with no re-set")
                consumed.add(key)
    return sorted(out)


def check_static(model, events):
    """Cross-check observed streams against the static world model.

    Both directions: every observed SET pattern must be compatible
    with some modeled construction-site pattern (`unmodeled` lists
    the strays — protocol surface the model misses), and the observed
    lifecycles must be clean (`violations`, as
    :func:`lifecycle_violations` with the model's exactly-once
    lanes).  Both empty == the run agrees with the model.
    """
    canons = set(model.pattern_table)
    unmodeled = set()
    for ev in events:
        if ev["op"] != "set":
            continue
        pat = normalize_concrete_key(ev["key"])
        if not any(patterns_compatible(c, pat) for c in canons):
            unmodeled.add(pat)
    return {
        "unmodeled": sorted(unmodeled),
        "violations": lifecycle_violations(events, model=model),
    }


def read_trace_dir(trace_dir):
    """Parse every kv-*.jsonl stream a multiprocess run left in
    `trace_dir`, skipping torn trailing lines (SIGKILL mid-write)."""
    events = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return events
    for name in names:
        if not (name.startswith("kv-") and name.endswith(".jsonl")):
            continue
        with open(os.path.join(trace_dir, name), encoding="utf-8",
                  errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue        # torn tail of a killed writer
                if isinstance(ev, dict) and "op" in ev:
                    events.append(ev)
    return events


def residual_keys(client, prefix="ptpu/", ignore=PERSISTENT_KEYS):
    """Keys still in the store under `prefix`, minus the reviewed
    persistent set — the end-of-test leak assertion
    (``assert not residual_keys(client)``)."""
    try:
        pairs = client.key_value_dir_get(prefix)
    except Exception:
        pairs = client.key_value_dir_get_bytes(prefix)
    return sorted(k for k, _v in pairs if k not in ignore)
