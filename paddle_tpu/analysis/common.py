"""Shared CLI plumbing for the baseline-gated analyzers.

tracelint, shardlint and racelint all ship the same surface: a finding
list, a checked-in fingerprint baseline, ``--check`` (fail only on NEW
findings), ``--write-baseline``, and a ``--json`` report carrying a
``"tool"`` discriminator over the shared ``analysis/report.to_json``
schema.  Before this module each CLI re-implemented that flow; the
third analyzer would have been the third copy.  The helpers here are
the one implementation — byte-identical output to what the two
original CLIs printed, which tests/test_racelint.py pins.

Pure stdlib (report.py is too): the CLIs must stay importable without
jax so the AST passes can gate CI in milliseconds.
"""
from __future__ import annotations

import json
import os
import sys

from paddle_tpu.analysis import report


def add_baseline_args(ap, default_baseline):
    """The flag set every baseline-gated analyzer CLI shares."""
    ap.add_argument("--check", action="store_true",
                    help="compare against the baseline; fail only on NEW "
                         "findings")
    ap.add_argument("--baseline", default=default_baseline,
                    help=f"baseline file (default {default_baseline})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the new baseline")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="also write findings as JSON ('-' for stdout)")
    return ap


def print_rules(rules, codes=None):
    """The ``--rules`` catalogue listing (one format for every tool)."""
    for r in rules.values():
        if codes is not None and r.code not in codes:
            continue
        print(f"{r.code}  {r.name}")
        print(f"    {r.message.format(detail='')}")
        print(f"    why: {r.rationale}")
        print(f"    fix: {r.fixit}")
    return 0


def run_baseline_flow(findings, args, tool, repo, elapsed,
                      show_source=True, json_extra=None):
    """The write-baseline / check-diff / report / json tail every
    analyzer CLI ends with.  Returns the process exit code: 0 clean,
    1 findings (plain mode) or NEW findings beyond the baseline
    (``--check``).

    - `args` must carry the :func:`add_baseline_args` flags.
    - `json_extra` is merged into the JSON doc AFTER the shared
      ``{"tool", "elapsed_s"}`` keys (shardlint appends its per-program
      cost reports there).
    """
    if args.write_baseline:
        report.write_baseline(findings, args.baseline)
        print(f"wrote baseline: {len(findings)} finding(s) -> "
              f"{os.path.relpath(args.baseline, repo)}")
        return 0

    shown = findings
    note = ""
    if args.check:
        baseline = report.load_baseline(args.baseline)
        shown = report.diff_vs_baseline(findings, baseline)
        note = (f" ({len(findings)} total, "
                f"{len(findings) - len(shown)} baselined)")

    if shown:
        print(report.format_text(shown, show_source=show_source))
    print(f"{tool}: {len(shown)} finding(s){note} "
          f"[{report.summarize(shown)}] in {elapsed:.2f}s")

    if args.json:
        extra = {"tool": tool, "elapsed_s": round(elapsed, 3)}
        extra.update(json_extra or {})
        doc = report.to_json(shown, extra=extra)
        if args.json == "-":
            json.dump(doc, sys.stdout, indent=1)
            print()
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1)
                fh.write("\n")
    return 1 if shown else 0
