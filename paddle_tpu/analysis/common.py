"""Shared CLI plumbing for the baseline-gated analyzers.

tracelint, shardlint, racelint and numlint all ship the same surface: a
finding list, a checked-in fingerprint baseline, ``--check`` (fail only
on NEW findings), ``--write-baseline``, ``--diff`` (baseline-vs-current
per-rule counts, informational), and a ``--json`` report carrying a
``"tool"`` discriminator over the shared ``analysis/report.to_json``
schema.  Before this module each CLI re-implemented that flow; the
third analyzer would have been the third copy.  The helpers here are
the one implementation — byte-identical ``--check`` output to what the
original CLIs printed, which tests/test_racelint.py pins.  The
``--diff`` table renderer is perfgate's, promoted here so the four
finding-based linters and the metric gate share one format.

Pure stdlib (report.py is too): the CLIs must stay importable without
jax so the AST passes can gate CI in milliseconds.
"""
from __future__ import annotations

import json
import os
import sys

from paddle_tpu.analysis import report


def add_baseline_args(ap, default_baseline):
    """The flag set every baseline-gated analyzer CLI shares."""
    ap.add_argument("--check", action="store_true",
                    help="compare against the baseline; fail only on NEW "
                         "findings")
    ap.add_argument("--baseline", default=default_baseline,
                    help=f"baseline file (default {default_baseline})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the new baseline")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="also write findings as JSON ('-' for stdout)")
    ap.add_argument("--diff", action="store_true",
                    help="render a baseline-vs-current per-rule count "
                         "table with signed deltas (informational: "
                         "always exit 0)")
    return ap


def print_rules(rules, codes=None):
    """The ``--rules`` catalogue listing (one format for every tool)."""
    for r in rules.values():
        if codes is not None and r.code not in codes:
            continue
        print(f"{r.code}  {r.name}")
        print(f"    {r.message.format(detail='')}")
        print(f"    why: {r.rationale}")
        print(f"    fix: {r.fixit}")
    return 0


def render_diff_table(baseline_map, current_map, title=None,
                      label="metric"):
    """The old-vs-new table renderer — promoted from tools/perfgate.py
    (which now delegates here) so every baseline-gated analyzer can
    offer a ``--diff`` mode over the same format.  Values on only one
    side are labeled "new"/"gone"; the % delta column is signed.
    Returns the rows as dicts (for ``--json``)."""
    rows = []
    if title is not None:
        print(f"== {title}")
    print(f"   {label:28s} {'baseline':>14s} {'current':>14s} "
          f"{'delta':>9s}")
    for m in sorted(set(baseline_map) | set(current_map)):
        b, c = baseline_map.get(m), current_map.get(m)
        if b is None:
            delta = "new"
        elif c is None:
            delta = "gone"
        elif b == 0:
            delta = "=" if c == 0 else "+inf"
        else:
            delta = f"{100.0 * (c / b - 1.0):+.1f}%"
        rows.append({label: m, "baseline": b, "current": c,
                     "delta": delta})
        fmt = lambda v: "-" if v is None else f"{v:,}" \
            if isinstance(v, int) else f"{v}"              # noqa: E731
        print(f"   {m:28s} {fmt(b):>14s} {fmt(c):>14s} {delta:>9s}")
    return rows


def _rule_counts_from_fingerprints(baseline):
    """Per-rule finding counts out of a fingerprint baseline — the
    fingerprint format is ``path::CODE::hash`` (analysis/report.py), so
    the rule code is recoverable without re-running the old tree."""
    counts = {}
    for fp, n in baseline.items():
        parts = fp.split("::")
        code = parts[1] if len(parts) == 3 else "?"
        counts[code] = counts.get(code, 0) + int(n)
    return counts


def run_baseline_flow(findings, args, tool, repo, elapsed,
                      show_source=True, json_extra=None):
    """The write-baseline / check-diff / report / json tail every
    analyzer CLI ends with.  Returns the process exit code: 0 clean,
    1 findings (plain mode) or NEW findings beyond the baseline
    (``--check``); ``--diff`` is informational and always exits 0.

    - `args` must carry the :func:`add_baseline_args` flags.
    - `json_extra` is merged into the JSON doc AFTER the shared
      ``{"tool", "elapsed_s"}`` keys (shardlint appends its per-program
      cost reports there).
    """
    if getattr(args, "diff", False):
        baseline = report.load_baseline(args.baseline)
        cur = {}
        for f in findings:
            cur[f.code] = cur.get(f.code, 0) + 1
        rows = render_diff_table(_rule_counts_from_fingerprints(baseline),
                                 cur, title=tool, label="rule")
        print(f"{tool}: --diff is informational "
              f"({len(findings)} current finding(s) in {elapsed:.2f}s)")
        # --diff COMPOSES with --check/--write-baseline (perfgate
        # semantics): the table is extra output, never a substitute for
        # the gate — an operator adding --diff to the CI command must
        # not silently disarm it.  The combined JSON comes from the
        # gate flow below; standalone --diff owns it.
        if not args.check and not args.write_baseline:
            if args.json:
                doc = {"tool": tool, "elapsed_s": round(elapsed, 3),
                       "diff": rows}
                if args.json == "-":
                    json.dump(doc, sys.stdout, indent=1)
                    print()
                else:
                    with open(args.json, "w", encoding="utf-8") as fh:
                        json.dump(doc, fh, indent=1)
                        fh.write("\n")
            return 0

    if args.write_baseline:
        report.write_baseline(findings, args.baseline)
        print(f"wrote baseline: {len(findings)} finding(s) -> "
              f"{os.path.relpath(args.baseline, repo)}")
        return 0

    shown = findings
    note = ""
    if args.check:
        baseline = report.load_baseline(args.baseline)
        shown = report.diff_vs_baseline(findings, baseline)
        note = (f" ({len(findings)} total, "
                f"{len(findings) - len(shown)} baselined)")

    if shown:
        print(report.format_text(shown, show_source=show_source))
    print(f"{tool}: {len(shown)} finding(s){note} "
          f"[{report.summarize(shown)}] in {elapsed:.2f}s")

    if args.json:
        extra = {"tool": tool, "elapsed_s": round(elapsed, 3)}
        extra.update(json_extra or {})
        doc = report.to_json(shown, extra=extra)
        if args.json == "-":
            json.dump(doc, sys.stdout, indent=1)
            print()
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1)
                fh.write("\n")
    return 1 if shown else 0
