"""kernlint KL1xx: static audit of Pallas kernel INTERIORS.

Every other analyzer in the lint_all stack stops at the ``pallas_call``
boundary — dtype_flow documents the body as deliberately opaque, the
roofline profiler costs call-boundary bytes only.  This module walks
*through* the boundary: a traced jaxpr's ``pallas_call`` eqn carries the
kernel jaxpr, the grid, and every in/out BlockMapping in ``eqn.params``,
which is enough to statically decide tile alignment (KL101), the VMEM
bill (KL102, via :mod:`vmem_model`), in-kernel accumulation dtypes
(KL103), ``input_output_aliases`` hazards (KL104), grid x block coverage
(KL105) and unguarded ragged tails (KL106) — all before XLA or Mosaic
ever see the kernel.

Two passes, same codes:

- :func:`check_kernels` — the jaxpr pass.  Findings resolve to real
  file:line through the eqn's jax source_info (so per-line
  ``# kernlint: disable=KLxxx`` comments apply), and fall back to a
  stable signature string when no user frame survives.
- :func:`check_kernel_files` — a pure-AST pass over ``ops/pallas/*.py``
  that needs no trace: conservative static twins of KL103 (dot-like
  call in a kernel body without ``preferred_element_type``) and KL101
  (literal block-shape tuples that no dtype's tile can satisfy).

Both passes honour the family-scoped suppression comments parsed by
:mod:`visitor` — a ``# kernlint: disable=ALL`` waives KL findings only,
and no foreign spelling can waive a KL code.
"""
from __future__ import annotations

import ast
import itertools
import os
from dataclasses import dataclass

from paddle_tpu.analysis import vmem_model
from paddle_tpu.analysis.dtype_flow import NARROW_FLOATS
from paddle_tpu.analysis.jaxpr_rules import _iter_eqns
from paddle_tpu.analysis.rules import KERNLINT_CODES, message_for
from paddle_tpu.analysis.shard_rules import (_REPO_ROOT, _mk_finding,
                                             apply_suppressions)
from paddle_tpu.analysis.visitor import (Finding, _dotted,
                                         parse_suppressions, rel_path)

__all__ = ["KernelConfig", "check_kernels", "check_kernel_files",
           "iter_pallas_eqns", "KERNLINT_CODES"]

_MIB = 1 << 20


@dataclass(frozen=True)
class KernelConfig:
    """Knobs for the KL rule family (one set shared by the CLI, the
    to_static(check=True) hook, and the tests)."""

    # KL102: per-call VMEM budget in MiB (None -> the default chip's
    # vmem_mb from observability.profile.ChipSpec)
    vmem_budget_mb: float = None
    # KL102: fraction of the budget the STATIC estimate may fill before
    # flagging — Mosaic's own spill overhead comes on top, so 1.0 means
    # "flag only what is already guaranteed over"
    vmem_fill_limit: float = 1.0
    # KL105: coverage enumeration stops beyond this many grid points
    grid_enum_cap: int = 4096


# --------------------------------------------------------------- plumbing
def iter_pallas_eqns(closed_jaxpr):
    """All ``pallas_call`` eqns of a (Closed)Jaxpr, however nested."""
    for eqn in _iter_eqns(closed_jaxpr):
        if eqn.primitive.name == "pallas_call":
            yield eqn


class _Call:
    """One decoded ``pallas_call`` eqn.  Every field is best-effort —
    missing params leave it empty and the rules that need it skip."""

    def __init__(self, eqn):
        self.eqn = eqn
        p = eqn.params
        self.name = (str(p.get("name_and_src_info", "") or "")
                     .split(" at ")[0]) or "<kernel>"
        gm = p.get("grid_mapping")
        self.grid = tuple(getattr(gm, "grid", ()) or ())
        bms = list(getattr(gm, "block_mappings", ()) or ())
        self.n_in = int(getattr(gm, "num_inputs", 0) or 0)
        self.n_out = int(getattr(gm, "num_outputs", 0) or 0)
        self.n_idx = int(getattr(gm, "num_index_operands", 0) or 0)
        self.in_bms = bms[:self.n_in]
        self.out_bms = bms[self.n_in:self.n_in + self.n_out]
        kj = p.get("jaxpr")
        self.kjaxpr = getattr(kj, "jaxpr", kj)
        self.aliases = tuple(p.get("input_output_aliases", ()) or ())
        self._body = None

    def all_bms(self):
        for bm in self.in_bms:
            yield bm, False
        for bm in self.out_bms:
            yield bm, True

    def body_eqns(self):
        if self._body is None:
            self._body = ([] if self.kjaxpr is None
                          else list(_iter_eqns(self.kjaxpr)))
        return self._body


def _origin(bm):
    return str(getattr(bm, "origin", "") or "<operand>")


def _bm_facts(bm):
    """(array_shape, block_dims, dtype) for one BlockMapping, or None
    when ranks disagree / params are unreadable."""
    sd = getattr(bm, "array_shape_dtype", None)
    dtype = getattr(sd, "dtype", None)
    ashape = tuple(int(s) for s in (getattr(sd, "shape", ()) or ()))
    dims = vmem_model._int_dims(getattr(bm, "block_shape", ()))
    if dtype is None or not dims or len(dims) != len(ashape):
        return None
    return ashape, dims, dtype


def _is_narrow(dtype):
    return getattr(dtype, "name", str(dtype)) in NARROW_FLOATS


def _out_dtype(eqn):
    try:
        return eqn.outvars[0].aval.dtype
    except Exception:
        return None


# ----------------------------------------------------------------- KL101
def _kl101(call, where):
    out = []
    for bm, _is_out in call.all_bms():
        facts = _bm_facts(bm)
        if facts is None:
            continue
        ashape, dims, dtype = facts
        sub, lane = vmem_model.native_tile(dtype)
        reqs = [(len(dims) - 1, lane)]
        if len(dims) >= 2:
            reqs.append((len(dims) - 2, sub))
        bad = []
        for pos, req in reqs:
            d, a = dims[pos], ashape[pos]
            # dim 1 (one row/lane at a time) and the full array extent
            # are both idiomatic and as tight as the array permits
            if d in (1, a) or d % req == 0:
                continue
            bad.append(f"dim {pos} = {d} needs a multiple of {req}")
        if bad:
            dname = getattr(dtype, "name", str(dtype))
            out.append(_mk_finding(
                "KL101",
                f"{tuple(dims)} for {dname} operand `{_origin(bm)}` of "
                f"kernel `{call.name}` ({'; '.join(bad)}; native tile "
                f"{vmem_model.native_tile(dtype)})",
                where, eqn=call.eqn,
                sig=f"{call.name} KL101 {_origin(bm)} {tuple(dims)}"))
    return out


# ----------------------------------------------------------------- KL102
def _kl102(call, config, where):
    est = vmem_model.estimate_vmem(call.eqn)
    if est.total_bytes <= 0:
        return []
    budget_mb, chip = config.vmem_budget_mb, ""
    if budget_mb is None:
        try:
            from paddle_tpu.observability import profile
            spec = profile.default_chip()
            budget_mb = float(getattr(spec, "vmem_mb", 16.0))
            chip = f" ({spec.name})"
        except Exception:
            budget_mb = 16.0
    limit = float(budget_mb) * float(config.vmem_fill_limit) * _MIB
    if est.total_bytes <= limit:
        return []
    return [_mk_finding(
        "KL102",
        f"{est.describe()} for kernel `{call.name}` exceeds the "
        f"{float(budget_mb):.0f} MiB/core VMEM budget{chip}",
        where, eqn=call.eqn, sig=f"{call.name} KL102")]


# ----------------------------------------------------------------- KL103
_REDUCE_PRIMS = ("reduce_sum", "cumsum", "cumlogsumexp")
_ADD_PRIMS = ("add", "add_any", "sub")


def _kl103(call, where):
    out = []
    eqns = call.body_eqns()
    producer = {}   # id(outvar) -> eqn
    get_src = {}    # id(outvar of a `get`) -> the ref var it read
    for beqn in eqns:
        for ov in beqn.outvars:
            producer[id(ov)] = beqn
        if beqn.primitive.name == "get" and beqn.invars:
            for ov in beqn.outvars:
                get_src[id(ov)] = beqn.invars[0]
    for beqn in eqns:
        prim = beqn.primitive.name
        odt = _out_dtype(beqn)
        if prim == "dot_general" and _is_narrow(odt):
            out.append(_mk_finding(
                "KL103",
                f"dot_general producing {odt.name} in `{call.name}` "
                f"(pass preferred_element_type=jnp.float32)",
                where, eqn=beqn,
                sig=f"{call.name} KL103 dot {odt.name}"))
        elif prim in _REDUCE_PRIMS and _is_narrow(odt):
            out.append(_mk_finding(
                "KL103",
                f"{prim} reduction carried in {odt.name} in "
                f"`{call.name}` (accumulate in float32 and cast on "
                f"the final store)",
                where, eqn=beqn,
                sig=f"{call.name} KL103 {prim} {odt.name}"))
        elif prim in ("swap", "addupdate") and len(beqn.invars) >= 2:
            val = beqn.invars[1]
            vdt = getattr(getattr(val, "aval", None), "dtype", None)
            if not _is_narrow(vdt):
                continue
            ref = beqn.invars[0]
            if prim == "addupdate":
                carried = True      # ref += narrow, by definition
            else:
                # read-modify-write of the SAME ref: the stored value
                # comes from an add/sub whose operand was `get(ref)`
                p = producer.get(id(val))
                carried = (p is not None
                           and p.primitive.name in _ADD_PRIMS
                           and any(get_src.get(id(iv)) is ref
                                   for iv in p.invars))
            if carried:
                out.append(_mk_finding(
                    "KL103",
                    f"accumulator ref `+=` in {vdt.name} in "
                    f"`{call.name}` (carry the running value in a "
                    f"float32 scratch ref)",
                    where, eqn=beqn,
                    sig=f"{call.name} KL103 carry {vdt.name}"))
    return out


# ----------------------------------------------------------------- KL104
def _kl104(call, where):
    out = []
    if not call.aliases:
        return out
    invars = list(getattr(call.kjaxpr, "invars", ()) or ())
    out_avals = tuple(call.eqn.params.get("out_avals", ()) or ())
    for pair in call.aliases:
        try:
            i_in, j_out = int(pair[0]), int(pair[1])
        except Exception:
            continue
        in_aval = None
        if i_in < len(call.eqn.invars):
            in_aval = getattr(call.eqn.invars[i_in], "aval", None)
        o_aval = out_avals[j_out] if j_out < len(out_avals) else None
        if in_aval is not None and o_aval is not None and (
                tuple(in_aval.shape) != tuple(o_aval.shape)
                or in_aval.dtype != o_aval.dtype):
            out.append(_mk_finding(
                "KL104",
                f"({i_in} -> {j_out}) of `{call.name}` alias "
                f"{in_aval.dtype.name}{list(in_aval.shape)} onto "
                f"{o_aval.dtype.name}{list(o_aval.shape)} — the "
                f"donated buffer cannot be reused in place",
                where, eqn=call.eqn,
                sig=f"{call.name} KL104 shape {i_in}->{j_out}"))
            continue
        # read-after-store: kernel invars are [scalar-prefetch refs,
        # in refs, out refs, scratch]; eqn invar i_in maps to kernel
        # invar i_in (prefetch operands lead both lists in order)
        in_ref = invars[i_in] if i_in < len(invars) else None
        oref_idx = call.n_idx + call.n_in + j_out
        out_ref = invars[oref_idx] if oref_idx < len(invars) else None
        if in_ref is None or out_ref is None:
            continue
        stored = False
        for beqn in call.body_eqns():
            prim = beqn.primitive.name
            if prim in ("swap", "addupdate") and beqn.invars \
                    and beqn.invars[0] is out_ref:
                stored = True
            elif stored and prim == "get" and beqn.invars \
                    and beqn.invars[0] is in_ref:
                out.append(_mk_finding(
                    "KL104",
                    f"({i_in} -> {j_out}) of `{call.name}` — aliased "
                    f"input read AFTER the aliased output was stored; "
                    f"the store already clobbered the shared buffer",
                    where, eqn=beqn,
                    sig=f"{call.name} KL104 raw {i_in}->{j_out}"))
                break
    return out


# ----------------------------------------------------------------- KL105
# index-map jaxprs are tiny affine programs; evaluating them in pure
# python (no jax dispatch) keeps full-grid enumeration cheap.  Any
# primitive outside this table -> the map is skipped, never guessed.
_PY_PRIMS = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "max": lambda a, b: max(a, b),
    "min": lambda a, b: min(a, b),
    "rem": lambda a, b: a % b if b else 0,
    "div": lambda a, b: int(a / b) if b else 0,   # lax.div truncates
    "neg": lambda a: -a,
    "clamp": lambda lo, x, hi: min(max(x, lo), hi),
    "convert_element_type": lambda a: a,
    "squeeze": lambda a: a,
    "broadcast_in_dim": lambda a: a,
    # the comparison/select set jnp's floor_divide expansion uses
    "sign": lambda a: (a > 0) - (a < 0),
    "eq": lambda a, b: int(a == b),
    "ne": lambda a, b: int(a != b),
    "lt": lambda a, b: int(a < b),
    "le": lambda a, b: int(a <= b),
    "gt": lambda a, b: int(a > b),
    "ge": lambda a, b: int(a >= b),
    "and": lambda a, b: int(bool(a) and bool(b)),
    "or": lambda a, b: int(bool(a) or bool(b)),
    "not": lambda a: int(not a),
    "select_n": lambda which, *cases: cases[int(which)],
}


class _Unsupported(Exception):
    pass


def _eval_int_jaxpr(jaxpr, consts, args):
    """Pure-python evaluation of a small integer jaxpr (no jax
    dispatch); ``pjit``/call wrappers are inlined recursively.  Raises
    _Unsupported on any primitive outside the table."""
    env = {}

    def read(v):
        if hasattr(v, "val"):          # Literal
            return int(v.val)
        return env[id(v)]

    for v, c in zip(jaxpr.constvars, consts):
        env[id(v)] = int(c)
    for v, a in zip(jaxpr.invars, args):
        env[id(v)] = int(a)
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        try:
            if prim in ("pjit", "closed_call", "core_call", "custom_jvp_call",
                        "custom_vjp_call"):
                sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
                subj = getattr(sub, "jaxpr", sub)
                if subj is None:
                    raise _Unsupported
                vals = _eval_int_jaxpr(subj,
                                       getattr(sub, "consts", ()) or (),
                                       [read(v) for v in eqn.invars])
                for ov, val in zip(eqn.outvars, vals):
                    env[id(ov)] = val
                continue
            fn = _PY_PRIMS.get(prim)
            if fn is None or len(eqn.outvars) != 1:
                raise _Unsupported
            env[id(eqn.outvars[0])] = int(fn(*[read(v)
                                               for v in eqn.invars]))
        except _Unsupported:
            raise
        except Exception:
            raise _Unsupported
    return tuple(int(read(v)) for v in jaxpr.outvars)


def _eval_index_map(imj, point):
    """Evaluate one index-map ClosedJaxpr at a grid point, pure python.
    Raises _Unsupported for data-dependent / non-affine maps."""
    jaxpr = getattr(imj, "jaxpr", None)
    if jaxpr is None or len(jaxpr.invars) != len(point):
        raise _Unsupported
    return _eval_int_jaxpr(jaxpr, list(getattr(imj, "consts", ()) or ()),
                           point)


def _kl105(call, config, where):
    out = []
    try:
        grid = [int(g) for g in call.grid]
    except Exception:
        return out                     # dynamic grid -> undecidable
    total = 1
    for g in grid:
        total *= max(1, g)
    if not grid or total <= 1 or total > config.grid_enum_cap:
        return out
    points = list(itertools.product(*[range(max(1, g)) for g in grid]))
    for bm, is_out in call.all_bms():
        facts = _bm_facts(bm)
        if facts is None:
            continue
        ashape, dims, _dtype = facts
        nblocks = [max(1, -(-a // d)) for a, d in zip(ashape, dims)]
        if len(nblocks) != len(getattr(bm, "block_shape", ()) or ()):
            continue
        imj = getattr(bm, "index_map_jaxpr", None)
        visits = {}                    # block tuple -> [step ordinals]
        try:
            for step, pt in enumerate(points):
                idx = _eval_index_map(imj, pt)
                if len(idx) != len(nblocks):
                    raise _Unsupported
                # Mosaic clamps block indices to the array bounds
                t = tuple(min(max(i, 0), n - 1)
                          for i, n in zip(idx, nblocks))
                visits.setdefault(t, []).append(step)
        except _Unsupported:
            continue                   # data-dependent map -> skip
        want = 1
        for n in nblocks:
            want *= n
        missing = want - len(visits)
        if missing:
            role = "output" if is_out else "operand"
            verb = "written" if is_out else "read"
            out.append(_mk_finding(
                "KL105",
                f"under-covers {role} `{_origin(bm)}` of "
                f"`{call.name}`: {missing} of {want} blocks never "
                f"{verb} (grid {tuple(grid)}, blocks "
                f"{tuple(nblocks)})",
                where, eqn=call.eqn,
                sig=f"{call.name} KL105 cover {_origin(bm)}"))
        if is_out:
            # revisiting an output block on CONSECUTIVE steps is the
            # accumulation idiom (the block stays resident in VMEM);
            # a NON-consecutive revisit re-fetches and double-writes
            for t, steps in visits.items():
                if steps != list(range(steps[0],
                                       steps[0] + len(steps))):
                    out.append(_mk_finding(
                        "KL105",
                        f"double-writes output block {t} of "
                        f"`{_origin(bm)}` in `{call.name}` on "
                        f"non-consecutive grid steps "
                        f"{steps[:4]}{'...' if len(steps) > 4 else ''}",
                        where, eqn=call.eqn,
                        sig=f"{call.name} KL105 dwrite {_origin(bm)}"))
                    break
    return out


# ----------------------------------------------------------------- KL106
_GUARD_PRIMS = ("cond", "iota", "select_n")


def _kl106(call, where):
    partials = []
    for bm, _is_out in call.all_bms():
        facts = _bm_facts(bm)
        if facts is None:
            continue
        ashape, dims, _dtype = facts
        for k, (a, d) in enumerate(zip(ashape, dims)):
            if d in (1, a) or d <= 0:
                continue
            if a % d:
                partials.append(
                    f"`{_origin(bm)}` dim {k}: {a} rows / {d}-row "
                    f"blocks leaves a {a % d}-row tail")
    if not partials:
        return []
    prims = {beqn.primitive.name for beqn in call.body_eqns()}
    if prims & set(_GUARD_PRIMS):
        return []                      # @pl.when / iota / where mask
    return [_mk_finding(
        "KL106",
        f"in `{call.name}` ({'; '.join(partials[:3])}; guard the tail "
        f"with @pl.when or an iota >= length mask)",
        where, eqn=call.eqn, sig=f"{call.name} KL106")]


# ------------------------------------------------------------ jaxpr pass
def check_kernels(closed_jaxpr, where="<traced program>", config=None,
                  suppress=True):
    """KL101..KL106 over every ``pallas_call`` eqn reachable from
    `closed_jaxpr`.  Duplicate findings (the same kernel traced once
    per layer) collapse to one."""
    config = config or KernelConfig()
    findings, seen = [], set()
    for eqn in iter_pallas_eqns(closed_jaxpr):
        call = _Call(eqn)
        for f in (_kl101(call, where) + _kl102(call, config, where)
                  + _kl103(call, where) + _kl104(call, where)
                  + _kl105(call, config, where) + _kl106(call, where)):
            key = (f.code, f.path, f.line, f.source_line)
            if key in seen:
                continue
            seen.add(key)
            findings.append(f)
    if suppress:
        findings = apply_suppressions(findings)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


# -------------------------------------------------------------- AST pass
_DOT_CALLS = ("dot", "matmul", "dot_general", "einsum", "tensordot")


def default_kernel_paths(root=None):
    d = os.path.join(root or _REPO_ROOT, "paddle_tpu", "ops", "pallas")
    if not os.path.isdir(d):
        return []
    return [os.path.join(d, f) for f in sorted(os.listdir(d))
            if f.endswith(".py")]


def _kernel_fns(tree):
    """FunctionDefs that look like Pallas kernel bodies: two or more
    ``*_ref`` parameters, or passed (possibly via functools.partial) as
    the first argument of a ``pallas_call``."""
    named, kernels = {}, {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            named.setdefault(node.name, node)
            a = node.args
            params = [x.arg for x in (a.posonlyargs + a.args)]
            if sum(1 for p in params if p.endswith("_ref")) >= 2:
                kernels[id(node)] = node
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _dotted(node.func).split(".")[-1] == "pallas_call"
                and node.args):
            continue
        a0 = node.args[0]
        if isinstance(a0, ast.Call) \
                and _dotted(a0.func).split(".")[-1] == "partial" \
                and a0.args:
            a0 = a0.args[0]
        if isinstance(a0, ast.Name) and a0.id in named:
            kernels[id(named[a0.id])] = named[a0.id]
    return list(kernels.values())


def _widened(call_node):
    """True when any argument is an explicit .astype(...float32...) —
    the idiom that widens a dot's operands by hand."""
    for a in call_node.args:
        if isinstance(a, ast.Call) and isinstance(a.func, ast.Attribute) \
                and a.func.attr == "astype" and a.args \
                and "float32" in ast.dump(a.args[0]):
            return True
    return False


def _static_kl103(rel, fn):
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted.split(".")[-1] not in _DOT_CALLS:
            continue
        if any(kw.arg == "preferred_element_type"
               for kw in node.keywords):
            continue
        if _widened(node):
            continue
        out.append(Finding(
            path=rel, line=node.lineno, col=node.col_offset,
            code="KL103",
            message=message_for(
                "KL103",
                detail=f"`{dotted}(...)` in kernel `{fn.name}` without "
                       f"preferred_element_type=jnp.float32 (the static "
                       f"pass cannot prove a wide accumulator)")))
    return out


def _static_kl101(rel, tree):
    """Literal block-shape tuples no dtype's tile can satisfy: a dim
    LARGER than the loosest (f32) tile requirement yet not a multiple
    of it is wrong for every dtype.  Smaller literals may equal the
    full array extent, which only the jaxpr pass can decide."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        last = _dotted(node.func).split(".")[-1]
        if last not in ("BlockSpec", "_vmem_spec"):
            continue
        tup = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "block_shape":
                tup = kw.value
        if not isinstance(tup, ast.Tuple) or len(tup.elts) < 1:
            continue
        dims = [e.value if isinstance(e, ast.Constant)
                and isinstance(e.value, int) else None
                for e in tup.elts]
        reqs = [(len(dims) - 1, vmem_model.LANE)]
        if len(dims) >= 2:
            reqs.append((len(dims) - 2, 8))
        bad = []
        for pos, req in reqs:
            d = dims[pos]
            if d is not None and d > req and d % req:
                bad.append(f"dim {pos} = {d} (needs a multiple of "
                           f"{req} for every dtype)")
        if bad:
            out.append(Finding(
                path=rel, line=node.lineno, col=node.col_offset,
                code="KL101",
                message=message_for(
                    "KL101",
                    detail=f"literal {tuple(dims)} — "
                           + "; ".join(bad))))
    return out


def check_kernel_files(paths=None):
    """The trace-free AST pass over Pallas kernel sources."""
    findings = []
    for path in (default_kernel_paths() if paths is None else paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src)
        except (OSError, SyntaxError):
            continue
        sup, skip = parse_suppressions(src)
        if skip:
            continue
        rel = rel_path(path, base=_REPO_ROOT)
        lines = src.splitlines()
        raw = _static_kl101(rel, tree)
        for fn in _kernel_fns(tree):
            raw.extend(_static_kl103(rel, fn))
        for f in raw:
            codes = sup.get(f.line, ())
            if "ALL" in codes or "ALL:KL" in codes or f.code in codes:
                continue
            if 1 <= f.line <= len(lines):
                f.source_line = lines[f.line - 1].strip()
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings
