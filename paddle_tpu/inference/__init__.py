"""Inference API. Reference: python/paddle/inference/__init__.py
(Config, create_predictor wrapping AnalysisPredictor).

TPU-native Predictor: the loaded/attached model's forward is frozen
(params become compile-time-donated constants or lifted inputs), AOT-compiled
by XLA into a single executable per input signature, with warmup — the
analogue of the reference's IR-pass + TensorRT engine path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    kCPU = 0
    kTPU = 4
    kGPU = 4


class Config:
    def __init__(self, prog_file=None, params_file=None):
        self.prog_file = prog_file
        self.params_file = params_file
        self._model = None
        self._use_tpu = True
        self._precision = PrecisionType.Bfloat16
        self._memory_pool_mb = 0

    def set_model(self, prog_file=None, params_file=None):
        self.prog_file = prog_file
        self.params_file = params_file

    def set_layer(self, layer, input_spec=None):
        """TPU-native: attach a live Layer (instead of a serialized program)."""
        self._model = layer
        self._input_spec = input_spec

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_tpu = True

    def enable_tpu(self):
        self._use_tpu = True

    def disable_gpu(self):
        self._use_tpu = False

    def enable_memory_optim(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_mkldnn(self):
        pass


class PredictTensor:
    """Handle mirroring PaddleTensor / ZeroCopyTensor."""

    def __init__(self, name, predictor):
        self.name = name
        self._predictor = predictor

    def copy_from_cpu(self, data):
        self._predictor._inputs[self.name] = jnp.asarray(np.asarray(data))

    def copy_to_cpu(self):
        return np.asarray(self._predictor._outputs[self.name])

    def shape(self):
        arr = self._predictor._inputs.get(self.name)
        if arr is None:
            arr = self._predictor._outputs.get(self.name)
        return list(arr.shape) if arr is not None else []


class Predictor:
    def __init__(self, config):
        self.config = config
        self._model = getattr(config, "_model", None)
        self._translated = None
        if self._model is None and config.prog_file:
            # serialized StableHLO program (jit.save with input_spec):
            # reload + run with no Python model class
            prefix = config.prog_file
            if prefix.endswith(".pdmodel"):
                prefix = prefix[: -len(".pdmodel")]
            from paddle_tpu.jit.serialization import load_program
            self._translated = load_program(
                prefix, params_path=config.params_file or None)
        elif self._model is None and config.params_file:
            import pickle
            with open(config.params_file, "rb") as f:
                self._params = pickle.load(f)
        self._inputs = {}
        self._outputs = {}
        self._compiled = {}
        if self._model is not None:
            self._model.eval()

    def get_input_names(self):
        return ["input_0"]

    def get_output_names(self):
        return list(self._outputs.keys()) or ["output_0"]

    def get_input_handle(self, name):
        return PredictTensor(name, self)

    def get_output_handle(self, name):
        return PredictTensor(name, self)

    def _get_compiled(self, avals):
        key = tuple((tuple(a.shape), str(a.dtype)) for a in avals)
        if key not in self._compiled:
            model = self._model
            params = {k: v._value for k, v in model.state_dict().items()}
            from paddle_tpu.jit.serialization import functional_forward
            self._compiled[key] = (jax.jit(functional_forward(model)), params)
        return self._compiled[key]

    def run(self, inputs=None):
        if inputs is not None:
            arrs = [jnp.asarray(np.asarray(x)) for x in inputs]
        else:
            arrs = [self._inputs[k] for k in sorted(self._inputs)]
        if self._translated is not None:
            out = self._translated(*arrs)
            outs = [o._value for o in (out if isinstance(out, list)
                                       else [out])]
        else:
            fn, params = self._get_compiled(arrs)
            outs = fn(params, *arrs)
        self._outputs = {f"output_{i}": o for i, o in enumerate(outs)}
        return [np.asarray(o) for o in outs]


def create_predictor(config):
    return Predictor(config)


def convert_to_mixed_precision(*args, **kwargs):
    raise NotImplementedError("planned: bf16 weight conversion pass")
