"""Inference API. Reference: python/paddle/inference/__init__.py
(Config, create_predictor wrapping AnalysisPredictor).

TPU-native Predictor: the loaded/attached model's forward is frozen
(params lifted to inputs), AOT-compiled by XLA into a single executable per
input signature (`jit(...).lower(...).compile()` — the analogue of the
reference's IR-pass + TensorRT engine build), with explicit warmup and
optional input-buffer donation (`Config.enable_memory_optim`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    kCPU = 0
    kTPU = 4
    kGPU = 4


class Config:
    def __init__(self, prog_file=None, params_file=None):
        self.prog_file = prog_file
        self.params_file = params_file
        self._model = None
        self._input_spec = None
        self._use_tpu = True
        self._precision = PrecisionType.Bfloat16
        self._memory_pool_mb = 0
        self._donate_inputs = False

    def set_model(self, prog_file=None, params_file=None):
        self.prog_file = prog_file
        self.params_file = params_file

    def set_layer(self, layer, input_spec=None):
        """TPU-native: attach a live Layer (instead of a serialized program)."""
        self._model = layer
        self._input_spec = input_spec

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_tpu = True

    def enable_tpu(self):
        self._use_tpu = True

    def disable_gpu(self):
        self._use_tpu = False

    def enable_memory_optim(self):
        """Donate input buffers to the executable (XLA reuses them for
        outputs). Handles must be re-bound (copy_from_cpu) between run()s."""
        self._donate_inputs = True

    def switch_ir_optim(self, flag=True):
        # XLA always runs its optimization pipeline; nothing to switch.
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_mkldnn(self):
        pass


class PredictTensor:
    """Handle mirroring PaddleTensor / ZeroCopyTensor."""

    def __init__(self, name, predictor):
        self.name = name
        self._predictor = predictor

    def copy_from_cpu(self, data):
        self._predictor._inputs[self.name] = jnp.asarray(np.asarray(data))

    def copy_to_cpu(self):
        return np.asarray(self._predictor._outputs[self.name])

    def shape(self):
        arr = self._predictor._inputs.get(self.name)
        if arr is None:
            arr = self._predictor._outputs.get(self.name)
        return list(arr.shape) if arr is not None else []


class Predictor:
    def __init__(self, config):
        self.config = config
        self._model = getattr(config, "_model", None)
        self._translated = None
        self._input_names = None
        self._output_names = None
        if self._model is None and config.prog_file:
            # serialized StableHLO program (jit.save with input_spec):
            # reload + run with no Python model class
            prefix = config.prog_file
            if prefix.endswith(".pdmodel"):
                prefix = prefix[: -len(".pdmodel")]
            from paddle_tpu.jit.serialization import load_program
            self._translated = load_program(
                prefix, params_path=config.params_file or None)
            self._input_names = list(self._translated.input_names)
            self._output_names = list(self._translated.output_names)
        elif self._model is None and config.params_file:
            from paddle_tpu.jit.serialization import load_params_npz
            self._params = load_params_npz(config.params_file)
        self._inputs = {}
        self._outputs = {}
        self._compiled = {}
        if self._model is not None:
            self._model.eval()
            self._input_names = self._derive_layer_input_names()

    def _derive_layer_input_names(self):
        spec = getattr(self.config, "_input_spec", None) or []
        names = []
        for i, s in enumerate(spec):
            names.append(getattr(s, "name", None) or f"input_{i}")
        if names:
            return names
        # fall back to the forward signature's positional arg names
        import inspect
        try:
            sig = inspect.signature(self._model.forward)
            return [p.name for p in sig.parameters.values()
                    if p.default is inspect.Parameter.empty
                    and p.kind in (p.POSITIONAL_ONLY,
                                   p.POSITIONAL_OR_KEYWORD)]
        except (TypeError, ValueError):
            return ["input_0"]

    def get_input_names(self):
        return list(self._input_names or ["input_0"])

    def get_output_names(self):
        return list(self._output_names or self._outputs.keys() or
                    ["output_0"])

    def get_input_handle(self, name):
        return PredictTensor(name, self)

    def get_output_handle(self, name):
        return PredictTensor(name, self)

    def _get_compiled(self, arrs):
        """AOT-compile the functionalized forward for this signature."""
        key = tuple((tuple(a.shape), str(a.dtype)) for a in arrs)
        if key not in self._compiled:
            model = self._model
            params = {k: v._value for k, v in model.state_dict().items()}
            from paddle_tpu.jit.serialization import functional_forward
            donate = (tuple(range(1, 1 + len(arrs)))
                      if self.config._donate_inputs else ())
            jitted = jax.jit(functional_forward(model),
                             donate_argnums=donate)
            compiled = jitted.lower(
                params, *[jax.ShapeDtypeStruct(a.shape, a.dtype)
                          for a in arrs]).compile()
            self._compiled[key] = (compiled, params)
        return self._compiled[key]

    def warmup(self, inputs=None):
        """Compile + run once on the bound (or given) inputs and discard the
        result, so the first run() serves at steady-state latency."""
        arrs = self._gather_inputs(inputs)
        if self._translated is not None:
            out = self._translated(*arrs)
            outs = out if isinstance(out, list) else [out]
            for o in outs:
                o._value.block_until_ready()
        else:
            fn, params = self._get_compiled(arrs)
            for o in fn(params, *arrs):
                o.block_until_ready()
            if self.config._donate_inputs:
                self._inputs = {}  # donated buffers are dead now
        return self

    def _gather_inputs(self, inputs):
        if inputs is not None:
            return [jnp.asarray(np.asarray(x)) for x in inputs]
        names = self.get_input_names()
        if self._inputs and all(n in self._inputs for n in names):
            return [self._inputs[n] for n in names]
        return [self._inputs[k] for k in sorted(self._inputs)]

    def serve(self, serving_config=None, **config_kw):
        """Serving adapter: lift the attached decoder Layer into a
        `paddle_tpu.serving.LLMEngine` (continuous batching, paged KV
        cache, bounded-recompile shape bucketing).

        The layer must follow the cache-aware forward contract
        (``model(input_ids, position_ids=..., kv_ctx=...)``; see
        `paddle_tpu.serving.LLMEngine` and `models/gpt.py`).  Config via
        a ready `serving.EngineConfig` or keyword args for one::

            config = inference.Config()
            config.set_layer(GPTForCausalLM(gpt3_tiny()))
            engine = inference.create_predictor(config).serve(
                max_num_seqs=8, max_model_len=256)
            engine.generate(prompts, sampling_params)
        """
        if self._model is None:
            raise RuntimeError(
                "Predictor.serve() needs a live Layer — use "
                "Config.set_layer(model); serialized StableHLO programs "
                "cannot take the kv_ctx serving hook")
        from paddle_tpu.serving import EngineConfig, LLMEngine
        if serving_config is None:
            serving_config = EngineConfig(**config_kw)
        elif config_kw:
            raise ValueError("pass either serving_config or kwargs, "
                             "not both")
        return LLMEngine(self._model, serving_config)

    def run(self, inputs=None):
        arrs = self._gather_inputs(inputs)
        if self._translated is not None:
            out = self._translated(*arrs)
            outs = [o._value for o in (out if isinstance(out, list)
                                       else [out])]
        else:
            fn, params = self._get_compiled(arrs)
            outs = fn(params, *arrs)
            if self.config._donate_inputs:
                self._inputs = {}  # donated buffers are dead now
        self._output_names = [f"output_{i}" for i in range(len(outs))]
        self._outputs = dict(zip(self._output_names, outs))
        return [np.asarray(o) for o in outs]


def create_predictor(config):
    return Predictor(config)


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, **kwargs):
    """Rewrite a serialized program's params to bf16/fp16 on disk.

    Reference: paddle.inference.convert_to_mixed_precision (an offline graph
    pass). TPU-native: the StableHLO program keeps its traced dtypes —
    TranslatedLayer casts params to the program's expected dtypes at call
    time — so this halves checkpoint size + host→device transfer; for a
    bf16 *compute* program, export under amp.auto_cast instead.
    """
    import ml_dtypes
    from paddle_tpu.jit.serialization import (load_params_npz,
                                              read_model_file,
                                              save_params_npz,
                                              write_model_file)

    if mixed_precision in (PrecisionType.Half, "float16", "fp16"):
        target = np.dtype(np.float16)
    elif mixed_precision in (None, PrecisionType.Bfloat16, "bfloat16",
                             "bf16"):
        target = np.dtype(ml_dtypes.bfloat16)
    else:
        raise ValueError(
            f"unsupported mixed_precision {mixed_precision!r}: only "
            f"bfloat16 (default) and float16 are supported")

    header, blob = read_model_file(model_file)
    params = load_params_npz(params_file)
    cast = {k: (v.astype(target)
                if np.issubdtype(v.dtype, np.floating) or
                v.dtype == np.dtype(ml_dtypes.bfloat16) else v)
            for k, v in params.items()}
    header.pop("version", None)
    header["mixed_precision"] = str(target)
    write_model_file(mixed_model_file, header, blob)
    save_params_npz(mixed_params_file, cast)


class DataType:
    """reference paddle/inference DataType enum."""

    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT64 = "int64"
    INT32 = "int32"
    INT8 = "int8"
    UINT8 = "uint8"
    BOOL = "bool"


def get_num_bytes_of_data_type(dtype):
    import numpy as np
    return np.dtype("float16" if dtype == DataType.BFLOAT16
                    else dtype).itemsize


def get_version():
    import paddle_tpu
    return f"paddle_tpu inference {paddle_tpu.__version__}"


def get_trt_compile_version():
    return (0, 0, 0)  # no TensorRT on TPU


def get_trt_runtime_version():
    return (0, 0, 0)


class PredictorPool:
    """Pool of predictors over one config (reference PredictorPool):
    on TPU each predictor shares the same AOT executable; the pool gives
    per-thread handle isolation."""

    def __init__(self, config, size=1):
        self._predictors = [create_predictor(config) for _ in range(size)]

    def retrive(self, idx):  # reference spells it 'retrive'
        return self._predictors[idx]

    retrieve = retrive
