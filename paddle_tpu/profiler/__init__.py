"""paddle_tpu.profiler — tracing/profiling subsystem.

Reference: python/paddle/profiler/profiler.py (Profiler with
ProfilerTargets, scheduler, on_trace_ready exporting Chrome traces via the
C++ HostTracer/CudaTracer). TPU-native: jax.profiler — traces capture XLA
compilation, TPU device activity, and host Python, viewable in
TensorBoard/Perfetto. RecordEvent maps to jax.profiler.TraceAnnotation.
"""
from __future__ import annotations

import contextlib
import enum
import os
import time

import jax


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2  # extension: the real target here


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """Step-state scheduler, same shape as the reference's make_scheduler.

    Contract (pinned by tests/test_observability.py): ``skip_first`` is
    consumed ONCE, before the first cycle; with ``repeat=0`` the
    closed/ready/record window then re-enters forever on a plain
    ``total``-step modulus (no re-skip at wraparound), and with
    ``repeat=n`` the scheduler stays CLOSED after n full cycles."""
    if closed < 0 or ready < 0 or repeat < 0 or skip_first < 0:
        raise ValueError("make_scheduler: negative phase lengths")
    if record < 1:
        raise ValueError("make_scheduler: record must be >= 1 (a window "
                         "that never records would never fire "
                         "on_trace_ready)")
    total = closed + ready + record

    def schedule(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready callback: jax writes TensorBoard/Perfetto traces into
    dir_name (the reference writes Chrome json; same consumer workflow).

    The handler itself writes a small capture manifest
    (``ptpu_trace_manifest.json``: trace dir, the recorded step window,
    capture UTC) next to the trace so a later report pass can tell WHICH
    steps a trace directory covers; the handler returns the manifest
    path (``handler.last_manifest_path`` keeps the most recent one)."""
    def handler(prof):
        # jax already wrote the trace into handler._ptpu_trace_dir; add
        # the manifest that names the capture window
        import json
        os.makedirs(handler._ptpu_trace_dir, exist_ok=True)
        window = {
            "step_window": [getattr(prof, "_window_start_step", 0),
                            getattr(prof, "step_num", 0)],
            "capture_utc": time.strftime("%Y-%m-%d %H:%M:%S UTC",
                                         time.gmtime()),
        }
        path = os.path.join(handler._ptpu_trace_dir,
                            "ptpu_trace_manifest.json")
        # a repeating scheduler fires once per recorded window while
        # every capture accumulates in the same dir — keep the full
        # window history ("windows"), top-level keys = most recent
        windows = []
        if os.path.exists(path):
            try:
                with open(path, encoding="utf-8") as fh:
                    windows = json.load(fh).get("windows", [])
            except (OSError, ValueError):
                windows = []
        windows.append(window)
        manifest = {
            "trace_dir": os.path.abspath(handler._ptpu_trace_dir),
            "worker_name": worker_name,
            "windows": windows,
            **window,
        }
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=1)
        handler.last_manifest_path = path
        return path
    # _begin_trace reads this; on_trace_ready itself only fires when a
    # recorded window's trace is ready (reference contract)
    handler._ptpu_trace_dir = dir_name
    handler.last_manifest_path = None
    return handler


export_protobuf = export_chrome_tracing


class Profiler:
    """paddle.profiler.Profiler-compatible surface over jax.profiler.

    Usage (same as reference):
        with Profiler(targets=[...], scheduler=(2, 5)) as p:
            for batch in loader:
                train_step(batch)
                p.step()
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None):
        self._trace_dir = os.path.join(os.getcwd(), "profiler_log")
        self.on_trace_ready = on_trace_ready
        if isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            if end - start < 1:
                # an empty (start, end) window has always meant "never
                # record"; keep that silent no-op rather than tripping
                # make_scheduler's record >= 1 validation
                self.scheduler = lambda step: ProfilerState.CLOSED
            else:
                self.scheduler = make_scheduler(
                    closed=max(start, 0), ready=0, record=end - start,
                    repeat=1)
        elif callable(scheduler):
            self.scheduler = scheduler
        else:
            self.scheduler = None
        self.timer_only = timer_only
        self.step_num = 0
        self._active = False
        self._step_times = []
        self._last_step_t = None

    # ---- lifecycle ----
    def start(self):
        self._last_step_t = time.perf_counter()
        if self.timer_only:
            return
        if self.scheduler is None:
            self._begin_trace()
        else:
            self._apply_state(self.scheduler(0))   # batch 0 is traceable

    def stop(self):
        if self._active:
            self._end_trace()

    def _begin_trace(self):
        if not self._active and not self.timer_only:
            custom_dir = getattr(self.on_trace_ready, "_ptpu_trace_dir",
                                 None)
            if custom_dir:
                self._trace_dir = custom_dir
            os.makedirs(self._trace_dir, exist_ok=True)
            jax.profiler.start_trace(self._trace_dir)
            self._active = True
            # first batch the open window covers (manifest step_window)
            self._window_start_step = self.step_num
            _profiler_mode[0] = True

    def _end_trace(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            _profiler_mode[0] = False
            # the reference contract: the callback fires only when a
            # recorded window's trace is ready
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self.step_num += 1
        if self.scheduler is not None:
            # step() marks the END of batch step_num-1; the new state covers
            # the UPCOMING batch step_num
            self._apply_state(self.scheduler(self.step_num))

    def _apply_state(self, state):
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._begin_trace()
        else:
            self._end_trace()

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        ts = np.asarray(self._step_times)
        return (f"avg {ts.mean()*1e3:.3f} ms, min {ts.min()*1e3:.3f} ms, "
                f"max {ts.max()*1e3:.3f} ms over {len(ts)} steps")

    def summary(self, **kwargs):
        return self.step_info()

    def export(self, path=None, format=None):
        pass  # traces are written by stop_trace

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class RecordEvent:
    """Annotate a host-side region so it shows up on the trace timeline
    (reference: paddle.profiler.RecordEvent -> here TraceAnnotation)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


@contextlib.contextmanager
def record_function(name):
    with jax.profiler.TraceAnnotation(name):
        yield


def start_profiler(dir_name="profiler_log"):
    os.makedirs(dir_name, exist_ok=True)
    jax.profiler.start_trace(dir_name)


def stop_profiler(dir_name=None):
    jax.profiler.stop_trace()


def load_profiler_result(path):
    raise NotImplementedError(
        "open the trace directory with TensorBoard or Perfetto")


class SortedKeys(enum.Enum):
    """Report sort orders (reference profiler/profiler_statistic.py)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(enum.Enum):
    """Summary table selectors (reference profiler/profiler.py:41)."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


_profiler_mode = [False]


def in_profiler_mode():
    return _profiler_mode[0]


def wrap_optimizers():
    """The reference monkey-patches optimizer.step for op annotation; our
    Optimizer.step already runs under RecordEvent when a profiler is
    active, so this is a no-op hook kept for API compatibility."""
    return None


class Benchmark:
    """Throughput/latency helper (reference profiler/utils.py Benchmark):
    wall-clock step timing with warmup discard."""

    def __init__(self):
        self._times = []
        self._t0 = None

    def begin(self):
        import time
        self._t0 = time.perf_counter()

    def step(self, num_samples=None):
        import time
        if self._t0 is not None:
            self._times.append((time.perf_counter() - self._t0,
                                num_samples or 1))
        self._t0 = time.perf_counter()

    def end(self):
        self._t0 = None

    def report(self, warmup=1):
        times = self._times[warmup:] or self._times
        if not times:
            return {}
        total_t = sum(t for t, _ in times)
        total_n = sum(n for _, n in times)
        return {"steps": len(times), "avg_ms": 1e3 * total_t / len(times),
                "ips": total_n / total_t if total_t else 0.0}


benchmark = Benchmark


# ---------------------------------------------------- statistic helpers
# (reference profiler/statistic_helper.py — interval algebra over
# [(start, end)] event ranges, used by the summary tables)
def merge_ranges(range_list1, range_list2, is_sorted=False):
    """Union of two interval lists (overlaps coalesced)."""
    ranges = list(range_list1 or []) + list(range_list2 or [])
    return merge_self_ranges(ranges)


def merge_self_ranges(src_ranges, is_sorted=False):
    if not src_ranges:
        return []
    rs = sorted(src_ranges)
    out = [list(rs[0])]
    for s, e in rs[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def intersection_ranges(range_list1, range_list2, is_sorted=False):
    a = merge_self_ranges(range_list1)
    b = merge_self_ranges(range_list2)
    out, i, j = [], 0, 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if s < e:
            out.append((s, e))
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def subtract_ranges(range_list1, range_list2, is_sorted=False):
    a = merge_self_ranges(range_list1)
    b = merge_self_ranges(range_list2)
    out = []
    j = 0
    for s, e in a:
        cur = s
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < e:
            if b[k][0] > cur:
                out.append((cur, b[k][0]))
            cur = max(cur, b[k][1])
            k += 1
        if cur < e:
            out.append((cur, e))
    return out


def sum_ranges(ranges):
    return sum(e - s for s, e in (ranges or []))


class Event:
    """One timeline event (reference profiler_statistic Event shape)."""

    def __init__(self, name, type=None, start_ns=0, end_ns=0):
        self.name = name
        self.type = type
        self.start_ns = start_ns
        self.end_ns = end_ns

    @property
    def duration_ns(self):
        return self.end_ns - self.start_ns


class HostStatisticNode:
    """Tree node over host events: self/children time accounting."""

    def __init__(self, hostnode):
        self.hostnode = hostnode
        self.children_node = []
        self.runtime_node = []
        self.cpu_time = 0
        self.self_cpu_time = 0

    def cal_statistic(self):
        for child in self.children_node:
            child.cal_statistic()
        self.cpu_time = self.hostnode.end_ns - self.hostnode.start_ns
        self.self_cpu_time = self.cpu_time - sum(
            c.cpu_time for c in self.children_node)


def traverse_tree(nodetrees):
    """Flatten {root: children-tree} into per-thread node lists."""
    out = {}
    for thread_id, root in (nodetrees or {}).items():
        stack = [root]
        flat = []
        while stack:
            node = stack.pop()
            flat.append(node)
            stack.extend(getattr(node, "children_node", []))
        out[thread_id] = flat
    return out


def get_device_nodes(hostnode):
    """All device-side nodes launched under a host node."""
    out = []
    stack = [hostnode]
    while stack:
        node = stack.pop()
        for rt in getattr(node, "runtime_node", []):
            out.extend(getattr(rt, "device_node", []))
        stack.extend(getattr(node, "children_node", []))
    return out


class TimeRangeSummary:
    """Per-event-type busy-time over the capture window."""

    def __init__(self):
        self.CPUTimeRange = {}
        self.GPUTimeRange = {}
        self.call_times = {}

    def add_range(self, kind, start_ns, end_ns, device=False):
        table = self.GPUTimeRange if device else self.CPUTimeRange
        table.setdefault(kind, []).append((start_ns, end_ns))
        self.call_times[kind] = self.call_times.get(kind, 0) + 1

    def get_cpu_range_sum(self, kind):
        return sum_ranges(merge_self_ranges(self.CPUTimeRange.get(kind)))

    def get_gpu_range_sum(self, kind):
        return sum_ranges(merge_self_ranges(self.GPUTimeRange.get(kind)))


class EventSummary:
    """Per-name aggregate: count/total/avg/min/max."""

    class Item:
        def __init__(self, name):
            self.name = name
            self.call = 0
            self.total_time = 0.0
            self.max_time = float("-inf")
            self.min_time = float("inf")

        @property
        def avg_time(self):
            return self.total_time / self.call if self.call else 0.0

        def add_item(self, duration):
            self.call += 1
            self.total_time += duration
            self.max_time = max(self.max_time, duration)
            self.min_time = min(self.min_time, duration)

    def __init__(self):
        self.items = {}

    def add_item(self, name, duration):
        self.items.setdefault(name, self.Item(name)).add_item(duration)


class MemorySummary:
    def __init__(self):
        self.allocated_items = {}
        self.reserved_items = {}
        self.peak_allocation_values = {}
        self.peak_reserved_values = {}


class DistributedSummary:
    def __init__(self):
        self.cpu_communication_range = []
        self.gpu_communication_range = []
        self.communication_range = []
        self.computation_range = []
        self.overlap_range = []

    def cal_overlap(self):
        self.communication_range = merge_ranges(
            self.cpu_communication_range, self.gpu_communication_range)
        self.overlap_range = intersection_ranges(
            self.communication_range, self.computation_range)


class StatisticData:
    """Bundle the summaries for report rendering (reference
    profiler_statistic.StatisticData)."""

    def __init__(self, node_trees=None, extra_info=None):
        self.node_trees = node_trees or {}
        self.extra_info = extra_info or {}
        self.time_range_summary = TimeRangeSummary()
        self.event_summary = EventSummary()
        self.distributed_summary = DistributedSummary()
        self.memory_summary = MemorySummary()


class TimeAverager:
    """Rolling step-time/ips averager (reference utils TimeAverager)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._total_time = 0.0
        self._total_samples = 0
        self._cnt = 0

    def record(self, usetime, num_samples=None):
        self._total_time += usetime
        self._cnt += 1
        if num_samples:
            self._total_samples += num_samples

    def get_average(self):
        return self._total_time / self._cnt if self._cnt else 0.0

    def get_ips_average(self):
        return self._total_samples / self._total_time \
            if self._total_time else 0.0


class Hook:
    def begin(self, benchmark=None):
        pass

    def end(self, benchmark=None):
        pass

    def before_reader(self, benchmark=None):
        pass

    def after_reader(self, benchmark=None):
        pass

    def after_step(self, benchmark=None):
        pass


class TimerHook(Hook):
    """Benchmark hook timing reader/step segments."""

    def __init__(self):
        self.reader_avg = TimeAverager()
        self.batch_avg = TimeAverager()
        self._reader_t0 = None
        self._step_t0 = None

    def before_reader(self, benchmark=None):
        self._reader_t0 = time.perf_counter()

    def after_reader(self, benchmark=None):
        if self._reader_t0 is not None:
            self.reader_avg.record(time.perf_counter() - self._reader_t0)

    def after_step(self, benchmark=None):
        if self._step_t0 is not None:
            self.batch_avg.record(time.perf_counter() - self._step_t0)
        self._step_t0 = time.perf_counter()


class Stack:
    """Simple LIFO used by the statistic tree walkers."""

    def __init__(self):
        self._items = []

    def push(self, item):
        self._items.append(item)

    def pop(self):
        return self._items.pop()

    def empty(self):
        return not self._items

    def top(self):
        return self._items[-1]


def wrap_tree(nodetrees):
    """Wrap raw host nodes into HostStatisticNode trees and compute
    self-times."""
    out = {}
    for tid, root in (nodetrees or {}).items():
        def build(n):
            w = HostStatisticNode(n)
            for c in getattr(n, "children_node", []):
                w.children_node.append(build(c))
            return w
        wrapped = build(root)
        wrapped.cal_statistic()
        out[tid] = wrapped
    return out


def get_profiler(*a, **kw):
    """Legacy entry (reference profiler/profiler.py get_profiler): routes
    to the utils facade over this module's Profiler."""
    from paddle_tpu.utils import get_profiler as _legacy
    return _legacy(*a, **kw)


# --------------------------------------------------------------------------
# Metrics-source registry: COMPATIBILITY SHIMS over the one process-wide
# paddle_tpu.observability registry.  Long-running subsystems (the serving
# LLMEngine, dataloader pools, ...) register a zero-arg snapshot callable;
# `metrics_report()` collects every registered snapshot — plus every
# observability Counter/Gauge/Histogram and the recompile log — into one
# dict, so a profiler pass over a serving process sees queue depth,
# tokens/s, TTFT, page utilization, compile counts AND recompile
# attribution alongside the device traces.  The imports are lazy so this
# module stays importable before the observability package loads.
def register_metrics_source(name, snapshot_fn):
    """Register `snapshot_fn` (zero-arg -> dict) under `name`.
    Re-registering a name replaces the previous source."""
    from paddle_tpu.observability import metrics as _obs_metrics
    return _obs_metrics.registry().register_source(name, snapshot_fn)


def unregister_metrics_source(name):
    from paddle_tpu.observability import metrics as _obs_metrics
    _obs_metrics.registry().unregister_source(name)


def metrics_report():
    """{source_name: snapshot_dict} for every registered source, plus
    the observability registry's own instruments under the
    ``"observability"`` key; a source that raises reports
    {"error": ...} instead of killing the whole report."""
    import paddle_tpu.observability as _obs  # registers builtin sources
    return _obs.registry().report()
