"""paddle.sysconfig parity (reference: python/paddle/sysconfig.py):
paths for building native extensions against the installed package."""
import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory holding the package's C headers (native/ ships the
    ctypes-backed runtime sources here)."""
    return os.path.join(_ROOT, "include")


def get_lib():
    """Directory holding the package's compiled native libraries."""
    return os.path.join(_ROOT, "native")
