"""Ring flash attention: Pallas flash blocks + ppermute ring (context
parallel).

Reference parity: ring/P2P sequence-parallel attention in reference-derived
suites (NCCL send/recv of k/v chunks overlapping per-chunk CUDA flash
kernels). TPU-native design: the per-step block attention is the Pallas
flash kernel (returning per-row lse so steps merge online-softmax style);
k/v chunks rotate with `lax.ppermute` over ICI; `lax.scan` +
`jax.checkpoint` keep residual memory at O(local chunk). The block kernel
carries a custom VJP for BOTH outputs (o, lse) — the lse cotangent folds
into the flash backward's delta term (ds = p·(dp − (Δ − d_lse))) — so
reverse-mode AD through the scan yields the reverse ring for free.

Chunk-level causality is resolved with `lax.switch` on the (traced) chunk
relation: fully-future chunks contribute a zero block (lse = −inf), the
diagonal chunk runs the causal kernel (skipping above-diagonal tiles), past
chunks run the dense kernel.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops.pallas.flash_attention import (
    DEFAULT_BLOCK_K,
    DEFAULT_BLOCK_Q,
    _flash_bwd_impl,
    _flash_fwd,
    _NEG_INF,
)
from paddle_tpu.distributed import mesh as mesh_mod


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_block(q, k, v, causal, scale, block_q, block_k, interpret):
    """Flash attention block returning (o, lse); differentiable in both."""
    return _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_block_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return (o, lse), (q, k, v, o, lse)


def _flash_block_bwd(causal, scale, block_q, block_k, interpret, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None and getattr(dlse, "dtype", None) != jax.dtypes.float0:
        # rows that never saw a key (lse == _NEG_INF sentinel, which is a
        # finite -1e30) have p == 0 everywhere — drop their lse cotangent
        delta = delta - jnp.where(lse > _NEG_INF / 2,
                                  dlse.astype(jnp.float32), 0.0)
    return _flash_bwd_impl(q, k, v, do, lse, delta, causal, scale,
                           block_q, block_k, interpret)


_flash_block.defvjp(_flash_block_fwd, _flash_block_bwd)


def _merge(o1, lse1, o2, lse2):
    """Merge two normalized partial-attention results by their lse.

    Rows no block has touched carry the (finite) _NEG_INF sentinel; compare
    against _NEG_INF/2 — NOT isfinite — to keep them inert."""
    m = jnp.maximum(lse1, lse2)
    seen = m > _NEG_INF / 2
    m_safe = jnp.where(seen, m, 0.0)
    w1 = jnp.exp(lse1 - m_safe)
    w2 = jnp.exp(lse2 - m_safe)
    tot = w1 + w2
    tot_safe = jnp.where(tot == 0.0, 1.0, tot)
    # fp32 out: the running accumulator must not round to bf16 every step
    o = (o1.astype(jnp.float32) * w1[..., None] +
         o2.astype(jnp.float32) * w2[..., None]) / tot_safe[..., None]
    lse = jnp.where(seen, m_safe + jnp.log(tot_safe), m)
    return o, lse


def ring_flash_attention(q, k, v, axis_name="sp", causal=False, scale=None,
                         axis_size=None, block_q=None, block_k=None,
                         interpret=None):
    """Ring attention with Pallas flash blocks, inside a shard_map body.

    q/k/v: [batch, heads, s_local, head_dim]; sequence sharded contiguously
    over `axis_name` (chunk index == axis index). Exact (matches full
    attention), differentiable, O(s_local²/ring-step) work on the diagonal.
    """
    if interpret is None:
        from paddle_tpu.ops.pallas import on_tpu
        interpret = not on_tpu()
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    scale = float(scale)
    block_q = block_q or DEFAULT_BLOCK_Q
    block_k = block_k or DEFAULT_BLOCK_K
    n = mesh_mod.resolve_axis_size(axis_name, axis_size)

    def blk(qx, kx, vx, c):
        # positional-only: custom_vjp rejects keyword args at call time
        return _flash_block(qx, kx, vx, c, scale, block_q, block_k,
                            bool(interpret))

    if n == 1:
        o, _ = blk(q, k, v, causal)
        return o

    my_idx = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    def zero_block(qx, kx, vx):
        return (jnp.zeros((b, h, sq, d), qx.dtype),
                jnp.full((b, h, sq), _NEG_INF, jnp.float32))

    def causal_block(qx, kx, vx):
        return blk(qx, kx, vx, True)

    def dense_block(qx, kx, vx):
        return blk(qx, kx, vx, False)

    def accumulate(o, lse, kt, vt, t):
        if causal:
            kv_idx = (my_idx - t) % n
            branch = jnp.where(kv_idx > my_idx, 0,
                               jnp.where(kv_idx == my_idx, 1, 2))
            ob, lseb = lax.switch(branch,
                                  [zero_block, causal_block, dense_block],
                                  q, kt, vt)
        else:
            ob, lseb = dense_block(q, kt, vt)
        return _merge(o, lse, ob, lseb)

    def step(carry, t):
        # permute at loop entry — n-1 ring hops, not n (t=0 runs pre-scan)
        o, lse, kt, vt = carry
        kt = lax.ppermute(kt, axis_name, perm)
        vt = lax.ppermute(vt, axis_name, perm)
        o, lse = accumulate(o, lse, kt, vt, t)
        return (o, lse, kt, vt), None

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    lse0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    o, lse = accumulate(o0, lse0, k, v, 0)
    carry, _ = lax.scan(jax.checkpoint(step), (o, lse, k, v),
                        jnp.arange(1, n))
    return carry[0].astype(q.dtype)


def ring_flash_attention_bshd(q, k, v, causal=False, scale=None,
                              axis_name="sp", mesh=None, interpret=None):
    """Whole-array wrapper: [batch, seq, heads, head_dim], seq sharded over
    `axis_name` of the mesh; owns the shard_map."""
    from paddle_tpu.distributed.context_parallel import wrap_bshd
    mesh = mesh or mesh_mod.ensure_mesh()
    fn = functools.partial(ring_flash_attention, axis_name=axis_name,
                           causal=causal, scale=scale,
                           axis_size=mesh.shape[axis_name],
                           interpret=interpret)
    return wrap_bshd(fn, q, k, v, axis_name, mesh)
