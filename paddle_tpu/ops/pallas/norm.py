"""Fused LayerNorm / RMSNorm Pallas kernels.

Reference parity: paddle/phi/kernels/gpu/layer_norm_kernel.cu (fused CUDA
layernorm). TPU-native: one VMEM pass per row block — mean/var/normalize/
affine fused in a single kernel (XLA already fuses these well; the kernel
removes the leftover HBM round-trips between the reduction and the scale).
Backward is the analytic formula in jnp (custom VJP) — fully fusible by XLA.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_ROWS = 128


def _vmem_spec(*args, **kwargs):
    if _VMEM is not None:
        kwargs["memory_space"] = _VMEM
    return pl.BlockSpec(*args, **kwargs)


def _on_tpu():
    from paddle_tpu.ops.pallas import on_tpu
    return on_tpu()


def _sublane(dtype):
    """MXU/VPU sublane count for a dtype (8 for f32, 16 for bf16) —
    the row granularity SL302-clean tile shapes are multiples of."""
    return max(8, 32 // max(1, jnp.dtype(dtype).itemsize))


def _auto_block_rows(rows, dtype, requested):
    """Block-row choice: the caller's request, else the smallest
    sublane multiple covering `rows` capped at DEFAULT_BLOCK_ROWS —
    small inputs then pay (at most) sublane-1 rows of padding instead
    of blowing up to a full 128-row block."""
    if requested:
        return int(requested)
    sub = _sublane(dtype)
    return min(DEFAULT_BLOCK_ROWS, -(-int(rows) // sub) * sub)


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps, has_affine):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    if has_affine:
        y = y * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    o_ref[:] = y.astype(o_ref.dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps, has_affine):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    if has_affine:
        y = y * w_ref[:].astype(jnp.float32)
    o_ref[:] = y.astype(o_ref.dtype)


def _pad_rows(x, block):
    pad = (-x.shape[0]) % block
    return jnp.pad(x, ((0, pad), (0, 0))) if pad else x


def _run_rows_kernel(kernel, x2, extras, block_rows, interpret):
    """Run a row-block kernel over [rows, hidden] (rows padded to block)."""
    rows, hidden = x2.shape
    xp = _pad_rows(x2, block_rows)
    grid = (xp.shape[0] // block_rows,)
    in_specs = [_vmem_spec((block_rows, hidden), lambda i: (i, 0))]
    for e in extras:
        in_specs.append(_vmem_spec((1, hidden), lambda i: (0, 0)))
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=_vmem_spec((block_rows, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(xp.shape, x2.dtype),
        interpret=interpret,
    )(xp, *[e[None, :] for e in extras])
    return out[:rows]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_layer_norm(x, weight, bias, eps=1e-5, block_rows=None,
                     interpret=None):
    """LayerNorm over the last axis. weight/bias may be None."""
    y, _, _ = _ln_fwd_impl(x, weight, bias, eps, block_rows, interpret)
    return y


def _ln_stats(x, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=-1, keepdims=True)
    return mean, jax.lax.rsqrt(var + eps)


def _ln_fwd_impl(x, weight, bias, eps, block_rows, interpret):
    if interpret is None:
        interpret = not _on_tpu()
    hidden = x.shape[-1]
    x2 = x.reshape(-1, hidden)
    has_affine = weight is not None
    kernel = functools.partial(_ln_kernel, eps=eps, has_affine=has_affine)
    if has_affine:
        b = bias if bias is not None else jnp.zeros_like(weight)
        extras = [weight, b]
    else:
        def kernel(x_ref, o_ref, *, _k=functools.partial(
                _ln_kernel, eps=eps, has_affine=False)):
            _k(x_ref, None, None, o_ref)
        extras = []
    y2 = _run_rows_kernel(kernel, x2, extras,
                          _auto_block_rows(x2.shape[0], x2.dtype,
                                           block_rows), interpret)
    return y2.reshape(x.shape), None, None


def _ln_fwd_rule(x, weight, bias, eps, block_rows, interpret):
    y = fused_layer_norm(x, weight, bias, eps, block_rows, interpret)
    return y, (x, weight, bias)


def _ln_bwd_jnp(x, weight, bias, g, eps):
    """Analytic LN backward in plain jnp (the no-affine / fallback
    path; the affine path runs the Pallas backward kernel below)."""
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    mean, rstd = _ln_stats(x, eps)
    xhat = (xf - mean) * rstd
    if weight is not None:
        gy = gf * weight.astype(jnp.float32)
    else:
        gy = gf
    # d/dx of layernorm (standard analytic form)
    dx = rstd * (gy - jnp.mean(gy, axis=-1, keepdims=True)
                 - xhat * jnp.mean(gy * xhat, axis=-1, keepdims=True))
    dx = dx.astype(x.dtype)
    red = tuple(range(x.ndim - 1))
    dw = (gf * xhat).sum(axis=red).astype(weight.dtype) \
        if weight is not None else None
    db = gf.sum(axis=red).astype(bias.dtype) if bias is not None else None
    return dx, dw, db


def _ln_bwd_rule(eps, block_rows, interpret, res, g):
    x, weight, bias = res
    if weight is None:
        return _ln_bwd_jnp(x, weight, bias, g, eps)
    # Pallas backward: recompute mean/rstd/xhat in-kernel from the
    # saved input (nothing normalized was materialized by the forward),
    # one fused pass producing dx + per-block dw/db partial sums
    dx, dw, db = _ln_bwd_pallas(x, weight, bias, g, None, eps, None,
                                block_rows, interpret)
    return dx, dw, db


fused_layer_norm.defvjp(_ln_fwd_rule, _ln_bwd_rule)


# ------------------------------------------------ fused residual + LN
def _gelu_grad(u):
    """(gelu(u), d gelu/du) — tanh approximation (the one F.gelu
    approximate=True uses)."""
    k = 0.7978845608028654   # sqrt(2/pi)
    c = 0.044715
    t = jnp.tanh(k * (u + c * u * u * u))
    y = 0.5 * u * (1.0 + t)
    dy = 0.5 * (1.0 + t) + 0.5 * u * (1.0 - t * t) * k \
        * (1.0 + 3.0 * c * u * u)
    return y, dy


def _ln_res_kernel(x_ref, r_ref, w_ref, b_ref, h_ref, y_ref, *, eps, act):
    """h = x + r; y = act(LN(h) * w + b) — one VMEM pass."""
    h = x_ref[:].astype(jnp.float32) + r_ref[:].astype(jnp.float32)
    mean = jnp.mean(h, axis=-1, keepdims=True)
    xc = h - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    y = y * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    if act == "gelu":
        y, _ = _gelu_grad(y)
    h_ref[:] = h.astype(h_ref.dtype)
    y_ref[:] = y.astype(y_ref.dtype)


def _ln_bwd_core(h, w, b, gy, gh, dx_ref, dwp_ref, dbp_ref, *, eps, act):
    mean = jnp.mean(h, axis=-1, keepdims=True)
    xc = h - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    if act == "gelu":
        u = xhat * w + b
        _, du = _gelu_grad(u)
        gy = gy * du
    gw = gy * w
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = rstd * (gw - m1 - xhat * m2)
    if gh is not None:
        dx = dx + gh
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dwp_ref[:] = jnp.sum(gy * xhat, axis=0, keepdims=True)
    dbp_ref[:] = jnp.sum(gy, axis=0, keepdims=True)


def _ln_bwd_kernel_plain(h_ref, gy_ref, w_ref, b_ref, dx_ref, dwp_ref,
                         dbp_ref, *, eps, act):
    # operand order = _run_ln_multi's: row-blocked inputs, then vectors
    _ln_bwd_core(h_ref[:].astype(jnp.float32), w_ref[:].astype(jnp.float32),
                 b_ref[:].astype(jnp.float32), gy_ref[:].astype(jnp.float32),
                 None, dx_ref, dwp_ref, dbp_ref, eps=eps, act=act)


def _ln_bwd_kernel_res(h_ref, gy_ref, gh_ref, w_ref, b_ref, dx_ref,
                       dwp_ref, dbp_ref, *, eps, act):
    _ln_bwd_core(h_ref[:].astype(jnp.float32), w_ref[:].astype(jnp.float32),
                 b_ref[:].astype(jnp.float32), gy_ref[:].astype(jnp.float32),
                 gh_ref[:].astype(jnp.float32), dx_ref, dwp_ref, dbp_ref,
                 eps=eps, act=act)


def _run_ln_multi(kernel, rows_in, vecs, rows_out_dtypes, n_partials,
                  block_rows, interpret):
    """Row-block kernel with several [rows, hidden] inputs/outputs plus
    per-block (grid, hidden) f32 partial-sum outputs (summed by the
    caller — the cross-block reduction is one tiny eqn)."""
    rows, hidden = rows_in[0].shape
    xp = [_pad_rows(a, block_rows) for a in rows_in]
    prows = xp[0].shape[0]
    grid = (prows // block_rows,)
    in_specs = [_vmem_spec((block_rows, hidden), lambda i: (i, 0))
                for _ in rows_in]
    in_specs += [_vmem_spec((1, hidden), lambda i: (0, 0)) for _ in vecs]
    out_specs = [_vmem_spec((block_rows, hidden), lambda i: (i, 0))
                 for _ in rows_out_dtypes]
    out_specs += [_vmem_spec((1, hidden), lambda i: (i, 0))
                  for _ in range(n_partials)]
    out_shape = [jax.ShapeDtypeStruct((prows, hidden), dt)
                 for dt in rows_out_dtypes]
    out_shape += [jax.ShapeDtypeStruct((grid[0], hidden), jnp.float32)
                  for _ in range(n_partials)]
    outs = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, interpret=interpret,
    )(*xp, *[v[None, :] for v in vecs])
    n_rows_out = len(rows_out_dtypes)
    return ([o[:rows] for o in outs[:n_rows_out]]
            + list(outs[n_rows_out:]))


def _ln_bwd_pallas(h, weight, bias, gy, gh, eps, act, block_rows,
                   interpret):
    """Shared Pallas LN backward: dx (+gh when given), dw, db."""
    if interpret is None:
        interpret = not _on_tpu()
    hidden = h.shape[-1]
    h2 = h.reshape(-1, hidden)
    gy2 = gy.reshape(-1, hidden)
    b = bias if bias is not None else jnp.zeros_like(weight)
    br = _auto_block_rows(h2.shape[0], h2.dtype, block_rows)
    if gh is None:
        kernel = functools.partial(_ln_bwd_kernel_plain, eps=eps, act=act)
        rows_in = [h2, gy2]
    else:
        kernel = functools.partial(_ln_bwd_kernel_res, eps=eps, act=act)
        rows_in = [h2, gy2, gh.reshape(-1, hidden)]
    dx2, dwp, dbp = _run_ln_multi(kernel, rows_in, [weight, b],
                                  [h.dtype], 2, br, interpret)
    dw = dwp.sum(axis=0).astype(weight.dtype)
    db = dbp.sum(axis=0).astype(bias.dtype) if bias is not None else None
    return dx2.reshape(h.shape), dw, db


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def fused_ln_residual(x, residual, weight, bias, eps=1e-5, act=None,
                      block_rows=None, interpret=None):
    """One-kernel ``h = x + residual; y = act(LN(h))`` returning
    ``(h, y)`` — the residual-stream update and the normalized input of
    the next sublayer in a single HBM pass.  The custom VJP saves only
    ``h`` (live on the forward path anyway) and RECOMPUTES mean/rstd in
    the backward kernel: no normalized intermediate is ever
    materialized.  ``weight`` is required (fall back to the pure-JAX
    composition for weight-free norms); ``act`` is None or ``"gelu"``
    (tanh approximation, for blocks whose norm feeds an activation
    directly)."""
    h, y = _ln_res_fwd_impl(x, residual, weight, bias, eps, act,
                            block_rows, interpret)
    return h, y


def _ln_res_fwd_impl(x, residual, weight, bias, eps, act, block_rows,
                     interpret):
    if interpret is None:
        interpret = not _on_tpu()
    hidden = x.shape[-1]
    x2 = x.reshape(-1, hidden)
    r2 = residual.reshape(-1, hidden)
    out_dtype = jnp.promote_types(x.dtype, residual.dtype)
    b = bias if bias is not None else jnp.zeros_like(weight)
    br = _auto_block_rows(x2.shape[0], jnp.dtype(out_dtype), block_rows)
    kernel = functools.partial(_ln_res_kernel, eps=eps, act=act)
    h2, y2 = _run_ln_multi(kernel, [x2, r2], [weight, b],
                           [out_dtype, out_dtype], 0, br, interpret)
    return h2.reshape(x.shape), y2.reshape(x.shape)


def _ln_res_fwd_rule(x, residual, weight, bias, eps, act, block_rows,
                     interpret):
    h, y = _ln_res_fwd_impl(x, residual, weight, bias, eps, act,
                            block_rows, interpret)
    # scalar zero sentinels carry the primal dtypes into the bwd rule
    # (residual pytree leaves must be jax values, not dtype objects)
    return (h, y), (h, weight, bias, jnp.zeros((), x.dtype),
                    jnp.zeros((), residual.dtype))


def _ln_res_bwd_rule(eps, act, block_rows, interpret, res, g):
    h, weight, bias, x_proto, r_proto = res
    gh, gy = g
    dh, dw, db = _ln_bwd_pallas(h, weight, bias, gy, gh, eps, act,
                                block_rows, interpret)
    dx = dh if dh.dtype == x_proto.dtype else dh.astype(x_proto.dtype)
    dres = dh if dh.dtype == r_proto.dtype else dh.astype(r_proto.dtype)
    return dx, dres, dw, db


fused_ln_residual.defvjp(_ln_res_fwd_rule, _ln_res_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def fused_add_layer_norm(x, residual, weight, bias, eps=1e-5, act=None,
                         block_rows=None, interpret=None):
    """Post-LN join ``y = act(LN(x + residual))`` returning ONLY y.

    Same kernels as :func:`fused_ln_residual`, for call sites where the
    summed stream is not consumed downstream (post-norm transformer
    blocks: the normalized value IS the stream).  Returning y alone
    means backward never materializes a zeros cotangent for an unused h
    output — h is still computed once and saved as the residual the
    backward kernel recomputes stats from."""
    _h, y = _ln_res_fwd_impl(x, residual, weight, bias, eps, act,
                             block_rows, interpret)
    return y


def _add_ln_fwd_rule(x, residual, weight, bias, eps, act, block_rows,
                     interpret):
    h, y = _ln_res_fwd_impl(x, residual, weight, bias, eps, act,
                            block_rows, interpret)
    return y, (h, weight, bias, jnp.zeros((), x.dtype),
               jnp.zeros((), residual.dtype))


def _add_ln_bwd_rule(eps, act, block_rows, interpret, res, gy):
    h, weight, bias, x_proto, r_proto = res
    dh, dw, db = _ln_bwd_pallas(h, weight, bias, gy, None, eps, act,
                                block_rows, interpret)
    dx = dh if dh.dtype == x_proto.dtype else dh.astype(x_proto.dtype)
    dres = dh if dh.dtype == r_proto.dtype else dh.astype(r_proto.dtype)
    return dx, dres, dw, db


fused_add_layer_norm.defvjp(_add_ln_fwd_rule, _add_ln_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def fused_rms_norm(x, weight, eps=1e-6, block_rows=None, interpret=None):
    """RMSNorm over the last axis. weight may be None."""
    if interpret is None:
        interpret = not _on_tpu()
    hidden = x.shape[-1]
    x2 = x.reshape(-1, hidden)
    has_affine = weight is not None
    if has_affine:
        kernel = functools.partial(_rms_kernel, eps=eps, has_affine=True)
        extras = [weight]
    else:
        def kernel(x_ref, o_ref):
            _rms_kernel(x_ref, None, o_ref, eps=eps, has_affine=False)
        extras = []
    y2 = _run_rows_kernel(kernel, x2, extras,
                          block_rows or DEFAULT_BLOCK_ROWS, interpret)
    return y2.reshape(x.shape)


def _rms_fwd_rule(x, weight, eps, block_rows, interpret):
    y = fused_rms_norm(x, weight, eps, block_rows, interpret)
    return y, (x, weight)


def _rms_bwd_rule(eps, block_rows, interpret, res, g):
    x, weight = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    xhat = xf * rstd
    gy = gf * weight.astype(jnp.float32) if weight is not None else gf
    dx = rstd * (gy - xhat * jnp.mean(gy * xhat, axis=-1, keepdims=True))
    dx = dx.astype(x.dtype)
    dw = (gf * xhat).sum(axis=tuple(range(x.ndim - 1))).astype(weight.dtype) \
        if weight is not None else None
    return dx, dw


fused_rms_norm.defvjp(_rms_fwd_rule, _rms_bwd_rule)
