"""Fused LayerNorm / RMSNorm Pallas kernels.

Reference parity: paddle/phi/kernels/gpu/layer_norm_kernel.cu (fused CUDA
layernorm). TPU-native: one VMEM pass per row block — mean/var/normalize/
affine fused in a single kernel (XLA already fuses these well; the kernel
removes the leftover HBM round-trips between the reduction and the scale).
Backward is the analytic formula in jnp (custom VJP) — fully fusible by XLA.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_ROWS = 128


def _vmem_spec(*args, **kwargs):
    if _VMEM is not None:
        kwargs["memory_space"] = _VMEM
    return pl.BlockSpec(*args, **kwargs)


def _on_tpu():
    from paddle_tpu.ops.pallas import on_tpu
    return on_tpu()


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps, has_affine):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    if has_affine:
        y = y * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    o_ref[:] = y.astype(o_ref.dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps, has_affine):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    if has_affine:
        y = y * w_ref[:].astype(jnp.float32)
    o_ref[:] = y.astype(o_ref.dtype)


def _pad_rows(x, block):
    pad = (-x.shape[0]) % block
    return jnp.pad(x, ((0, pad), (0, 0))) if pad else x


def _run_rows_kernel(kernel, x2, extras, block_rows, interpret):
    """Run a row-block kernel over [rows, hidden] (rows padded to block)."""
    rows, hidden = x2.shape
    xp = _pad_rows(x2, block_rows)
    grid = (xp.shape[0] // block_rows,)
    in_specs = [_vmem_spec((block_rows, hidden), lambda i: (i, 0))]
    for e in extras:
        in_specs.append(_vmem_spec((1, hidden), lambda i: (0, 0)))
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=_vmem_spec((block_rows, hidden), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(xp.shape, x2.dtype),
        interpret=interpret,
    )(xp, *[e[None, :] for e in extras])
    return out[:rows]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_layer_norm(x, weight, bias, eps=1e-5, block_rows=None,
                     interpret=None):
    """LayerNorm over the last axis. weight/bias may be None."""
    y, _, _ = _ln_fwd_impl(x, weight, bias, eps, block_rows, interpret)
    return y


def _ln_stats(x, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=-1, keepdims=True)
    return mean, jax.lax.rsqrt(var + eps)


def _ln_fwd_impl(x, weight, bias, eps, block_rows, interpret):
    if interpret is None:
        interpret = not _on_tpu()
    hidden = x.shape[-1]
    x2 = x.reshape(-1, hidden)
    has_affine = weight is not None
    kernel = functools.partial(_ln_kernel, eps=eps, has_affine=has_affine)
    if has_affine:
        b = bias if bias is not None else jnp.zeros_like(weight)
        extras = [weight, b]
    else:
        def kernel(x_ref, o_ref, *, _k=functools.partial(
                _ln_kernel, eps=eps, has_affine=False)):
            _k(x_ref, None, None, o_ref)
        extras = []
    y2 = _run_rows_kernel(kernel, x2, extras,
                          block_rows or DEFAULT_BLOCK_ROWS, interpret)
    return y2.reshape(x.shape), None, None


def _ln_fwd_rule(x, weight, bias, eps, block_rows, interpret):
    y = fused_layer_norm(x, weight, bias, eps, block_rows, interpret)
    return y, (x, weight, bias)


def _ln_bwd_rule(eps, block_rows, interpret, res, g):
    x, weight, bias = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    mean, rstd = _ln_stats(x, eps)
    xhat = (xf - mean) * rstd
    n = x.shape[-1]
    if weight is not None:
        gy = gf * weight.astype(jnp.float32)
    else:
        gy = gf
    # d/dx of layernorm (standard analytic form)
    dx = rstd * (gy - jnp.mean(gy, axis=-1, keepdims=True)
                 - xhat * jnp.mean(gy * xhat, axis=-1, keepdims=True))
    dx = dx.astype(x.dtype)
    red = tuple(range(x.ndim - 1))
    dw = (gf * xhat).sum(axis=red).astype(weight.dtype) \
        if weight is not None else None
    db = gf.sum(axis=red).astype(bias.dtype) if bias is not None else None
    return dx, dw, db


fused_layer_norm.defvjp(_ln_fwd_rule, _ln_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def fused_rms_norm(x, weight, eps=1e-6, block_rows=None, interpret=None):
    """RMSNorm over the last axis. weight may be None."""
    if interpret is None:
        interpret = not _on_tpu()
    hidden = x.shape[-1]
    x2 = x.reshape(-1, hidden)
    has_affine = weight is not None
    if has_affine:
        kernel = functools.partial(_rms_kernel, eps=eps, has_affine=True)
        extras = [weight]
    else:
        def kernel(x_ref, o_ref):
            _rms_kernel(x_ref, None, o_ref, eps=eps, has_affine=False)
        extras = []
    y2 = _run_rows_kernel(kernel, x2, extras,
                          block_rows or DEFAULT_BLOCK_ROWS, interpret)
    return y2.reshape(x.shape)


def _rms_fwd_rule(x, weight, eps, block_rows, interpret):
    y = fused_rms_norm(x, weight, eps, block_rows, interpret)
    return y, (x, weight)


def _rms_bwd_rule(eps, block_rows, interpret, res, g):
    x, weight = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    xhat = xf * rstd
    gy = gf * weight.astype(jnp.float32) if weight is not None else gf
    dx = rstd * (gy - xhat * jnp.mean(gy * xhat, axis=-1, keepdims=True))
    dx = dx.astype(x.dtype)
    dw = (gf * xhat).sum(axis=tuple(range(x.ndim - 1))).astype(weight.dtype) \
        if weight is not None else None
    return dx, dw


fused_rms_norm.defvjp(_rms_fwd_rule, _rms_bwd_rule)
