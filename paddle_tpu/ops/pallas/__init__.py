"""Pallas TPU kernels (flash attention, fused norms, ring attention)."""
from __future__ import annotations

import jax


def compute_platform() -> str:
    """Platform the computation will actually run on: the installed mesh's
    devices if any (a CPU mesh can be active while the default backend is a
    real TPU chip — e.g. the driver's virtual-device dryrun), else the
    default backend."""
    try:
        from paddle_tpu.distributed.mesh import get_mesh
        m = get_mesh()
        if m is not None:
            return m.devices.flat[0].platform
    except Exception:  # pragma: no cover
        pass
    try:
        return jax.devices()[0].platform
    except Exception:  # pragma: no cover
        return "cpu"


def on_tpu() -> bool:
    return compute_platform() == "tpu"
