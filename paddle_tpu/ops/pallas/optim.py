"""Fused single-pass optimizer update kernels.

The per-param Python loop in ``Optimizer.step`` emits ~30 small HLO ops
per parameter — the PR 8 roofline attributed ~35x param-bytes per step
to ``optimizer.step`` (the top row of the whole train program, 40% of
gpt_hybrid_train's bytes).  Algebra can't fix that: the cost model (and
the pre-fusion HLO) charges every elementwise intermediate.  A fused
kernel can: one ``pallas_call`` per parameter reads p, g, m, v exactly
once and writes p', m', v' exactly once (~7x param bytes; ~5x with
bf16 moments), with the update math in f32 registers.

CPU runs the same kernel in interpret mode (pure-JAX numerics, same
traced program — so tools/perfgate.py's deterministic budget measures
the real fused traffic).  Traced scalars (lr, bias corrections) ride in
one (1, 4) f32 operand so LR schedules never retrigger compilation.

Update math is kept EQN-FOR-EQN identical to the unfused
``Adam._update_param`` / ``AdamW._update_param`` path (same op order,
division by (1-beta^t) rather than multiply-by-reciprocal), so the
fused step is numerically interchangeable with the loop it replaces —
tests/test_bytesopt.py pins them allclose at 1e-6.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas.norm import _vmem_spec

__all__ = ["fused_adam_update", "supports_fused"]

# per-operand block-bytes ceiling: 7 live refs per grid step must fit
# VMEM (~16 MB/core) with room for double buffering
_BLOCK_BYTES = 1 << 21


def supports_fused(shape):
    """The fused kernel handles rank-2 parameters (the natural MXU
    layout every Linear/Embedding weight already has).  Rank-1 biases
    and norm scales stay on the unfused loop — they are <1% of the
    bytes and a reshape eqn per operand would cost more than it saves."""
    return len(tuple(shape)) == 2


def _pick_block_rows(rows, row_bytes):
    """Largest power-of-two row block that divides `rows` and keeps a
    block under _BLOCK_BYTES; falls back to the whole array (single
    block) for odd row counts."""
    br = 8
    while br * 2 <= rows and rows % (br * 2) == 0 \
            and (br * 2) * row_bytes <= _BLOCK_BYTES:
        br *= 2
    if rows % br != 0:
        return rows
    return br


def _adam_kernel(sc_ref, p_ref, g_ref, m_ref, v_ref, po_ref, mo_ref,
                 vo_ref, *gq_ref, beta1, beta2, eps, weight_decay,
                 guard=False):
    """One fused AdamW step for one row block.

    sc = [lr, 1-beta1^t, 1-beta2^t, decay_on] — the traced scalars.
    Matches the unfused loop exactly: decoupled decay first (AdamW),
    then moment updates, bias correction by DIVISION, update, apply.

    ``guard=True`` (the training-sentinel probe) additionally reduces
    the block's gradient sum-of-squares in f32 — g is ALREADY in
    registers, so the probe adds zero extra HBM traffic — writes it to
    the per-block partials output, and GATES the block's commit on its
    finiteness: a block whose gradients are non-finite writes back the
    UNMODIFIED p/m/v (the zero-update skip), selected per step by data
    so the compiled program never changes."""
    lr = sc_ref[0, 0]
    c1 = sc_ref[0, 1]
    c2 = sc_ref[0, 2]
    decay_on = sc_ref[0, 3]
    p0 = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = m_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    p = p0
    if weight_decay:
        # decoupled (AdamW) decay; decay_on gates it per-param
        # (apply_decay_param_fun) without a second kernel variant
        p = p * (1.0 - decay_on * lr * weight_decay)
    new_m = beta1 * m + (1.0 - beta1) * g
    new_v = beta2 * v + (1.0 - beta2) * g * g
    mhat = new_m / c1
    vhat = new_v / c2
    upd = lr * mhat / (jnp.sqrt(vhat) + eps)
    new_p = p - upd
    if guard:
        gsq = jnp.sum(g * g)
        good = jnp.isfinite(gsq)
        # jnp.where, not multiply: NaN * 0 is NaN, select is clean
        new_p = jnp.where(good, new_p, p0)
        new_m = jnp.where(good, new_m, m)
        new_v = jnp.where(good, new_v, v)
        gq_ref[0][:] = jnp.full(gq_ref[0].shape, gsq, jnp.float32)
    po_ref[:] = new_p.astype(po_ref.dtype)
    mo_ref[:] = new_m.astype(mo_ref.dtype)
    vo_ref[:] = new_v.astype(vo_ref.dtype)


def fused_adam_update(p, g, m, v, lr, c1, c2, *, beta1, beta2, eps,
                      weight_decay=0.0, decay_on=True, guard=False,
                      interpret=None):
    """Single-pass Adam/AdamW update of one rank-2 parameter.

    Returns ``(p', m', v')``.  ``lr``/``c1``/``c2`` are traced scalars
    (learning rate and the 1-beta^t bias corrections); ``beta1/beta2/
    eps/weight_decay`` are static.  ``weight_decay`` non-zero applies
    DECOUPLED decay (AdamW) gated by ``decay_on``; plain Adam passes 0
    and handles coupled decay in the gradient as before.  Moments keep
    their storage dtype (bf16 moments read/write half the bytes; math
    stays f32 in-kernel).

    ``guard=True`` returns ``(p', m', v', partials)`` where
    ``partials[i, 0]`` is row-block ``i``'s gradient sum-of-squares
    (f32, reduced in-kernel — the sentinel probe's zero-extra-read
    path) and each block's commit is gated on its own finiteness (the
    zero-update skip; docs/resilience.md "Numerics sentinel" has the
    region-granularity contract).  The partials rows are 128 lanes
    wide (the block scalar broadcast) to stay a legal TPU tile; the
    caller reads column 0.
    """
    if interpret is None:
        from paddle_tpu.ops.pallas import on_tpu
        interpret = not on_tpu()
    rows, cols = p.shape
    br = _pick_block_rows(rows, cols * 4)
    grid = (rows // br,)
    sc = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(c1, jnp.float32),
        jnp.asarray(c2, jnp.float32),
        jnp.asarray(1.0 if decay_on else 0.0, jnp.float32),
    ]).reshape(1, 4)
    kernel = functools.partial(_adam_kernel, beta1=float(beta1),
                               beta2=float(beta2), eps=float(eps),
                               weight_decay=float(weight_decay),
                               guard=bool(guard))
    blk = lambda i: (i, 0)          # noqa: E731 — row-block index map
    out_specs = [_vmem_spec((br, cols), blk) for _ in range(3)]
    out_shape = [
        jax.ShapeDtypeStruct(p.shape, p.dtype),
        jax.ShapeDtypeStruct(m.shape, m.dtype),
        jax.ShapeDtypeStruct(v.shape, v.dtype),
    ]
    if guard:
        out_specs.append(_vmem_spec((1, 128), blk))
        out_shape.append(
            jax.ShapeDtypeStruct((grid[0], 128), jnp.float32))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[_vmem_spec((1, 4), lambda i: (0, 0))]
        + [_vmem_spec((br, cols), blk) for _ in range(4)],
        out_specs=out_specs,
        out_shape=out_shape,
        # in-place param/moment updates: the donated input buffers ARE
        # the outputs on TPU (no extra HBM copies)
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(sc, p, g, m, v)
