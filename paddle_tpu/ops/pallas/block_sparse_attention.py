"""Block-sparse flash attention (Pallas) — the sparse_attention fast path.

Reference role: python/paddle/nn/functional/sparse_attention.py wraps a
CUDA kernel that computes attention only at the CSR-described positions.
TPU-native design: sparsity is expressed at BLOCK granularity (the MXU
computes (block_q x block_k) tiles or nothing), and the kernel never
visits an inactive block at all — a host-built table lists, for every
q-block, its active k-blocks padded to the row maximum, and the grid's
innermost dimension walks that table (the splash-attention structure:
work is proportional to the ACTIVE block count, not seq²). The table
rides in scalar-prefetch memory so the K/V BlockSpec index maps read it
to DMA exactly the active blocks.

Supports the patterns block-sparse attention exists for — sliding
window, global tokens, blocked-causal, arbitrary static masks — via
`make_block_mask` helpers or any [nq, nk] boolean array. The pattern
must be CONCRETE (host numpy): sparsity layouts are architectural
constants, not data.

Backward: custom VJP recomputes with the same active-block tables
(dq walks the q-row tables; dk/dv walk the transposed k-column tables),
so the backward is block-sparse too.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas.flash_attention import (_LOG2E, _LN2, _NEG_INF,
                                                  _LSE_LANES, _compiler_params,
                                                  _pad_to)

__all__ = ["block_sparse_attention", "block_sparse_flash_attention",
           "prepare_block_mask", "make_sliding_window_mask",
           "make_global_plus_window_mask", "block_mask_tables"]


def make_sliding_window_mask(nq, nk, window_blocks, causal=True):
    """[nq, nk] bool: each q-block attends its diagonal neighborhood."""
    qi = np.arange(nq)[:, None]
    ki = np.arange(nk)[None, :]
    m = np.abs(qi - ki) < window_blocks
    if causal:
        m &= ki <= qi
    return m


def make_global_plus_window_mask(nq, nk, window_blocks, global_blocks,
                                 causal=True):
    """Sliding window + the first `global_blocks` k-blocks visible to
    every query (the Longformer/BigBird pattern at block granularity)."""
    m = make_sliding_window_mask(nq, nk, window_blocks, causal)
    m[:, :global_blocks] = True
    if causal:
        m &= np.arange(nk)[None, :] <= np.arange(nq)[:, None]
    return m


def block_mask_tables(block_mask):
    """Host-side: [nq, nk] bool -> (kt, counts, max_active) where
    kt[qi, j] is the j-th active k-block of q-row qi (padded with the
    row's last active block so padded steps re-DMA a resident block and
    the copy is elided)."""
    bm = np.asarray(block_mask, bool)
    nq, nk = bm.shape
    counts = bm.sum(1).astype(np.int32)
    max_active = int(counts.max()) if counts.size else 0
    if max_active == 0:
        raise ValueError("block mask has no active blocks")
    kt = np.zeros((nq, max_active), np.int32)
    for qi in range(nq):
        act = np.nonzero(bm[qi])[0]
        if len(act) == 0:
            act = np.array([0])
        kt[qi, :len(act)] = act
        kt[qi, len(act):] = act[-1]
    return kt, counts, max_active


def _fwd_kernel(kt_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, block_q, block_k,
                num_steps, seq_k):
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(j < cnt_ref[qi])
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = (scale * _LOG2E) * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if seq_k % block_k:
            # ragged tail: zero-padded K tokens must not enter the
            # softmax denominator (phantom e^0 weights)
            col = kt_ref[qi, j] * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(col < seq_k, s, _NEG_INF)
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_steps - 1)
    def _finalize():
        m = m_ref[:, 0:1]
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(m * _LN2 + jnp.log(l_safe),
                                      lse_ref[0].shape)


def _bsa_fwd(q, k, v, kt, counts, scale, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    qp = _pad_to(q, block_q, 2)
    kp = _pad_to(k, block_k, 2)
    vp = _pad_to(v, block_k, 2)
    bh = b * h
    qp = qp.reshape(bh, -1, d)
    kp = kp.reshape(bh, -1, d)
    vp = vp.reshape(bh, -1, d)
    nq = qp.shape[1] // block_q
    max_active = kt.shape[1]

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        num_steps=max_active, seq_k=sk)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, max_active),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bhid, qi, j, kt_, cnt_: (bhid, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bhid, qi, j, kt_, cnt_:
                         (bhid, kt_[qi, j], 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bhid, qi, j, kt_, cnt_:
                         (bhid, kt_[qi, j], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bhid, qi, j, kt_, cnt_: (bhid, qi, 0)),
            pl.BlockSpec((1, block_q, _LSE_LANES),
                         lambda bhid, qi, j, kt_, cnt_: (bhid, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
    )
    o, lse8 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, qp.shape[1], d), q.dtype),
            jax.ShapeDtypeStruct((bh, qp.shape[1], _LSE_LANES),
                                 jnp.float32),
        ],
        compiler_params=_compiler_params(("parallel", "parallel",
                                          "arbitrary")),
        interpret=interpret,
    )(kt, counts, qp, kp, vp)
    o = o.reshape(b, h, -1, d)[:, :, :sq, :]
    lse = lse8[:, :, 0].reshape(b, h, -1)[:, :, :sq]
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def block_sparse_flash_attention(q, k, v, block_mask_key, scale, block_q,
                                 block_k, interpret):
    """q/k/v: [batch, heads, seq_q, d] / [.., seq_k, d].

    block_mask_key: a _BlockMaskTables from prepare_block_mask() (hashable
    static carrier of the host-side tables). Returns [b, h, seq_q, d].
    """
    o, _ = _bsa_fwd(q, k, v, block_mask_key.kt_arr(),
                    block_mask_key.cnt_arr(), scale, block_q, block_k,
                    interpret)
    return o


class _BlockMaskTables:
    """Hashable static carrier for the block tables (custom_vjp nondiff
    args must be hashable)."""

    def __init__(self, block_mask, block_q, block_k):
        self.kt, self.counts, self.max_active = block_mask_tables(
            block_mask)
        bm = np.asarray(block_mask, bool)
        # transpose tables for dk/dv: active q-blocks per k-block
        self.qt, self.qcounts, self.qmax = block_mask_tables(bm.T)
        self._key = (bm.tobytes(), bm.shape, block_q, block_k)

    def kt_arr(self):
        return jnp.asarray(self.kt)

    def cnt_arr(self):
        return jnp.asarray(self.counts)

    def qt_arr(self):
        return jnp.asarray(self.qt)

    def qcnt_arr(self):
        return jnp.asarray(self.qcounts)

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, _BlockMaskTables) and \
            self._key == other._key


def prepare_block_mask(block_mask, block_q, block_k):
    return _BlockMaskTables(block_mask, block_q, block_k)


def _bsa_fwd_rule(q, k, v, tables, scale, block_q, block_k, interpret):
    o, lse = _bsa_fwd(q, k, v, tables.kt_arr(), tables.cnt_arr(), scale,
                      block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _bsa_bwd_rule(tables, scale, block_q, block_k, interpret, res, do):
    """Block-sparse backward by recompute: dq accumulates over each
    q-row's active k-blocks; dk/dv over each k-column's active q-blocks.
    Implemented with jnp gathers over the SAME tables (one fused XLA
    loop per direction) — the FLOP count is proportional to the active
    blocks, matching the forward's sparsity."""
    q, k, v, o, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    kt = tables.kt_arr()
    cnt = tables.cnt_arr()
    qt = tables.qt_arr()
    qcnt = tables.qcnt_arr()
    nq = kt.shape[0]
    nk = qt.shape[0]

    qb = _pad_to(q, block_q, 2).reshape(b * h, nq, block_q, d)
    kb = _pad_to(k, block_k, 2).reshape(b * h, nk, block_k, d)
    vb = _pad_to(v, block_k, 2).reshape(b * h, nk, block_k, d)
    dob = _pad_to(do, block_q, 2).reshape(b * h, nq, block_q, d)
    lseb = _pad_to(lse, block_q, 2).reshape(b * h, nq, block_q)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), -1)
    deltab = _pad_to(delta, block_q, 2).reshape(b * h, nq, block_q)

    def p_block(qx, kx, ls, kj):
        s = jnp.einsum("bqd,bkd->bqk", qx.astype(jnp.float32),
                       kx.astype(jnp.float32)) * scale
        if sk % block_k:
            col = kj * block_k + jnp.arange(block_k)
            s = jnp.where(col[None, None, :] < sk, s, -jnp.inf)
        return jnp.exp(s - ls[..., None])

    # ---- dq: walk each q-row's active k-blocks ----
    def dq_row(qi, carry):
        dq = carry

        def step(j, acc):
            kj = kt[qi, j]
            kx = kb[:, kj]
            vx = vb[:, kj]
            p = p_block(qb[:, qi], kx, lseb[:, qi], kj)
            dp = jnp.einsum("bqd,bkd->bqk", dob[:, qi].astype(jnp.float32),
                            vx.astype(jnp.float32))
            ds = p * (dp - deltab[:, qi][..., None])
            upd = scale * jnp.einsum("bqk,bkd->bqd", ds,
                                     kx.astype(jnp.float32))
            return acc + jnp.where(j < cnt[qi], upd, 0.0)

        row = jax.lax.fori_loop(0, kt.shape[1], step,
                                jnp.zeros_like(dq[:, qi]))
        return dq.at[:, qi].set(row)

    dq = jax.lax.fori_loop(
        0, nq, dq_row, jnp.zeros_like(qb, jnp.float32))

    # ---- dk/dv: walk each k-column's active q-blocks ----
    def dkv_col(ki, carry):
        dk, dv = carry

        def step(j, accs):
            ak, av = accs
            qi = qt[ki, j]
            p = p_block(qb[:, qi], kb[:, ki], lseb[:, qi], ki)
            dvu = jnp.einsum("bqk,bqd->bkd", p,
                             dob[:, qi].astype(jnp.float32))
            dp = jnp.einsum("bqd,bkd->bqk", dob[:, qi].astype(jnp.float32),
                            vb[:, ki].astype(jnp.float32))
            ds = p * (dp - deltab[:, qi][..., None])
            dku = scale * jnp.einsum("bqk,bqd->bkd", ds,
                                     qb[:, qi].astype(jnp.float32))
            keep = j < qcnt[ki]
            return (ak + jnp.where(keep, dku, 0.0),
                    av + jnp.where(keep, dvu, 0.0))

        ck, cv = jax.lax.fori_loop(
            0, qt.shape[1], step,
            (jnp.zeros_like(dk[:, ki]), jnp.zeros_like(dv[:, ki])))
        return dk.at[:, ki].set(ck), dv.at[:, ki].set(cv)

    dk, dv = jax.lax.fori_loop(
        0, nk, dkv_col,
        (jnp.zeros_like(kb, jnp.float32), jnp.zeros_like(vb, jnp.float32)))

    dq = dq.reshape(b, h, -1, d)[:, :, :sq].astype(q.dtype)
    dk = dk.reshape(b, h, -1, d)[:, :, :sk].astype(k.dtype)
    dv = dv.reshape(b, h, -1, d)[:, :, :sk].astype(v.dtype)
    return dq, dk, dv


block_sparse_flash_attention.defvjp(_bsa_fwd_rule, _bsa_bwd_rule)


def block_sparse_attention(q, k, v, block_mask, block_q=512, block_k=512,
                           scale=None, interpret=None):
    """Public entry: q/k/v [batch, heads, seq, d]; block_mask [nq, nk]
    bool (host numpy) with nq = ceil(seq_q/block_q), nk =
    ceil(seq_k/block_k). Work and DMA are proportional to the ACTIVE
    block count."""
    if interpret is None:
        from paddle_tpu.ops.pallas import on_tpu
        interpret = not on_tpu()
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    nq = -(-q.shape[2] // block_q)
    nk = -(-k.shape[2] // block_k)
    bm = np.asarray(block_mask, bool)
    if bm.shape != (nq, nk):
        raise ValueError(
            f"block_mask shape {bm.shape} != (ceil(sq/bq), ceil(sk/bk)) "
            f"= {(nq, nk)}")
    tables = prepare_block_mask(bm, block_q, block_k)
    return block_sparse_flash_attention(q, k, v, tables, float(scale),
                                        block_q, block_k, bool(interpret))
