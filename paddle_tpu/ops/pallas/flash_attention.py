"""Flash attention for TPU (Pallas), forward + custom-VJP backward.

Reference parity: the reference exposes fused attention via
paddle.incubate.nn.functional.fused_attention / flash-attn CUDA kernels
(paddle/phi/kernels/gpu/flash_attn_kernel.cu in later branches). TPU-native
design: an online-softmax kernel tiled for the MXU with a 3-D grid
(batch*heads, q-blocks, k-blocks) — K/V stream through VMEM one
`block_k` slice at a time (so 16k+ sequences never pin the whole K/V in
the ~16MB VMEM), the running (acc, m, l) state lives in VMEM scratch that
persists across the innermost k-block grid dimension, and causal blocks
strictly above the diagonal skip their compute via `pl.when`.

Mosaic tiling: every block's trailing two dims are either (8,128)-aligned
or cover the full array dim. The log-sum-exp is carried as a
`[bh, seq, 8]` array (the scalar per row replicated across 8 lanes) —
a `(block_q, 8)` tile is legal where the naive `(1, block_q)` block that
round 2 shipped is not.

Layouts: public entry `flash_attention_bshd` takes paddle's [batch, seq,
heads, head_dim]; kernels run in [batch, heads, seq, head_dim].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Hard dependency: the 3-D-grid kernels carry their online-softmax state in
# VMEM scratch (pltpu.VMEM), which interpret mode also supports — a JAX
# build without pallas.tpu cannot run this module at all.
from jax.experimental.pallas import tpu as pltpu

_VMEM = pltpu.VMEM

DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30
_LSE_LANES = 8  # lse/delta replicated across this many lanes for tiling


def _vmem_spec(*args, **kwargs):
    kwargs["memory_space"] = _VMEM
    return pl.BlockSpec(*args, **kwargs)


def _compiler_params(dims):
    for name in ("CompilerParams", "TPUCompilerParams"):
        cls = getattr(pltpu, name, None)
        if cls is not None:
            try:
                return cls(dimension_semantics=dims)
            except Exception:  # pragma: no cover - API drift
                continue
    return None  # pragma: no cover


def _round_up(n, m):
    return -(-n // m) * m


def _scratch(shape, dtype=jnp.float32):
    return pltpu.VMEM(shape, dtype)


_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453


def _mask_block(s, qi, ki, block_q, block_k, causal, seq_k, seq_q=None):
    """Apply causal/edge masking to one [bq, bk] score tile. The mask is
    skipped STATICALLY when no block can need it (dense attention on
    block-aligned sequences) — a traced per-block `lax.cond` measures
    slower than just masking, so the only branch here is at trace time."""
    ragged = (seq_k % block_k != 0) or (
        seq_q is not None and seq_q % block_q != 0)
    if not causal and not ragged:
        return s
    bq, bk = s.shape
    col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = col < seq_k
    row = None
    if causal or seq_q is not None:
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (bq, bk), 0)
    if causal:
        mask = jnp.logical_and(mask, col <= row)
    if seq_q is not None:
        mask = jnp.logical_and(mask, row < seq_q)
    return jnp.where(mask, s, _NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                scale, causal, block_q, block_k, seq_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: blocks strictly above the diagonal contribute nothing — skip
    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else ki >= 0

    @pl.when(run)
    def _compute():
        # dots run in the input dtype (bf16 hits the MXU at full rate) with
        # fp32 accumulation; softmax statistics stay fp32 throughout.
        q = q_ref[0]                                      # [bq, d]
        k = k_ref[0]                                      # [bk, d]
        v = v_ref[0]
        # base-2 softmax: fold scale*log2(e) into the score multiply so the
        # per-element exp is a bare exp2; m/l are tracked in the log2 domain
        s = (scale * _LOG2E) * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk] f32
        s = _mask_block(s, qi, ki, block_q, block_k, causal, seq_k)
        m_prev = m_ref[:, 0:1]                            # [bq, 1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp2(s - m_new)
        corr = jnp.exp2(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        m = m_ref[:, 0:1]                                 # log2-domain max
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(m * _LN2 + jnp.log(l_safe),
                                      lse_ref[0].shape)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, block_q, block_k, seq_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else ki >= 0

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        do = do_ref[0]
        lse2 = lse_ref[0][:, 0:1] * _LOG2E               # log2 domain
        delta = delta_ref[0][:, 0:1]
        k = k_ref[0]
        v = v_ref[0]
        s = (scale * _LOG2E) * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        s = _mask_block(s, qi, ki, block_q, block_k, causal, seq_k)
        p = jnp.exp2(s - lse2)                            # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[...] = dq_acc[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, seq_q, seq_k):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # causal: q blocks strictly before this k block see none of it — skip
    run = (qi * block_q + block_q - 1 >= ki * block_k) if causal else qi >= 0

    @pl.when(run)
    def _compute():
        k = k_ref[0]                                      # [bk, d]
        v = v_ref[0]
        q = q_ref[0]                                      # [bq, d]
        do = do_ref[0]
        lse2 = lse_ref[0][:, 0:1] * _LOG2E               # log2 domain
        delta = delta_ref[0][:, 0:1]
        s = (scale * _LOG2E) * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        s = _mask_block(s, qi, ki, block_q, block_k, causal, seq_k,
                        seq_q=seq_q)
        p = jnp.exp2(s - lse2)                            # [bq, bk]
        dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _pad_to(x, mult, axis):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _pick_blocks(sq, sk, block_q, block_k):
    """Clamp block sizes to the (16-aligned) sequence lengths so short
    sequences get a single full-array block (always Mosaic-legal)."""
    return (min(block_q, _round_up(sq, 16)), min(block_k, _round_up(sk, 16)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q, block_k = _pick_blocks(sq, sk, block_q, block_k)
    qp = _pad_to(q, block_q, 2)
    kp = _pad_to(k, block_k, 2)
    vp = _pad_to(v, block_k, 2)
    sqp, skp = qp.shape[2], kp.shape[2]
    qp = qp.reshape(b * h, sqp, d)
    kp = kp.reshape(b * h, skp, d)
    vp = vp.reshape(b * h, skp, d)

    grid = (b * h, sqp // block_q, skp // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_k=sk)
    # causal: clamp the k index map to the diagonal so the skipped
    # above-diagonal steps re-map to an already-resident block and Pallas
    # elides their K/V DMA entirely (pl.when alone skips compute, not the
    # prefetch)
    if causal:
        def kv_index(bh, qi, ki):
            return (bh, jnp.minimum(
                ki, (qi * block_q + block_q - 1) // block_k), 0)
    else:
        def kv_index(bh, qi, ki):
            return (bh, ki, 0)
    o, lse8 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _vmem_spec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            _vmem_spec((1, block_k, d), kv_index),
            _vmem_spec((1, block_k, d), kv_index),
        ],
        out_specs=[
            _vmem_spec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            _vmem_spec((1, block_q, _LSE_LANES),
                       lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sqp, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sqp, _LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, d)),
            _scratch((block_q, 128)),
            _scratch((block_q, 128)),
        ],
        compiler_params=_compiler_params(("parallel", "parallel",
                                          "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    o = o.reshape(b, h, sqp, d)[:, :, :sq, :]
    lse = lse8[:, :, 0].reshape(b, h, sqp)[:, :, :sq]
    return o, lse


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                    # [b,h,sq]
    return _flash_bwd_impl(q, k, v, do, lse, delta, causal, scale,
                           block_q, block_k, interpret)


def _rep_lanes(x, block, bh):
    """[b,h,sq] → [bh, sq_padded, _LSE_LANES] (value replicated per lane)."""
    xp = _pad_to(x, block, 2).reshape(bh, -1)
    return jnp.broadcast_to(xp[..., None], xp.shape + (_LSE_LANES,))


def _flash_bwd_impl(q, k, v, do, lse, delta, causal, scale, block_q, block_k,
                    interpret):
    """dq/dk/dv given precomputed delta (= sum(do*o) for the plain kernel;
    ring attention folds the lse cotangent in as delta - dlse)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q, block_k = _pick_blocks(sq, sk, block_q, block_k)

    bh = b * h
    qp = _pad_to(q, block_q, 2).reshape(bh, -1, d)
    dop = _pad_to(do, block_q, 2).reshape(bh, -1, d)
    lsep = _rep_lanes(lse, block_q, bh)
    deltap = _rep_lanes(delta, block_q, bh)
    kp = _pad_to(k, block_k, 2).reshape(bh, -1, d)
    vp = _pad_to(v, block_k, 2).reshape(bh, -1, d)
    sqp, skp = qp.shape[1], kp.shape[1]
    nq, nk = sqp // block_q, skp // block_k

    # causal DMA elision (see _flash_fwd): skipped blocks re-map to a
    # resident block index so their copies are elided
    if causal:
        def kv_index(bh, qi, ki):
            return (bh, jnp.minimum(
                ki, (qi * block_q + block_q - 1) // block_k), 0)

        def q_index(bh, ki, qi):
            return (bh, jnp.maximum(qi, (ki * block_k) // block_q), 0)
    else:
        def kv_index(bh, qi, ki):
            return (bh, ki, 0)

        def q_index(bh, ki, qi):
            return (bh, qi, 0)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_k=sk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            _vmem_spec((1, block_k, d), kv_index),
            _vmem_spec((1, block_k, d), kv_index),
            _vmem_spec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            _vmem_spec((1, block_q, _LSE_LANES),
                       lambda bh, qi, ki: (bh, qi, 0)),
            _vmem_spec((1, block_q, _LSE_LANES),
                       lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=_vmem_spec((1, block_q, d),
                             lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sqp, d), q.dtype),
        scratch_shapes=[_scratch((block_q, d))],
        compiler_params=_compiler_params(("parallel", "parallel",
                                          "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_q=sq, seq_k=sk)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=[
            _vmem_spec((1, block_q, d), q_index),
            _vmem_spec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            _vmem_spec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            _vmem_spec((1, block_q, d), q_index),
            _vmem_spec((1, block_q, _LSE_LANES), q_index),
            _vmem_spec((1, block_q, _LSE_LANES), q_index),
        ],
        out_specs=[
            _vmem_spec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            _vmem_spec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, skp, d), k.dtype),
            jax.ShapeDtypeStruct((bh, skp, d), v.dtype),
        ],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        compiler_params=_compiler_params(("parallel", "parallel",
                                          "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)

    dq = dq.reshape(b, h, sqp, d)[:, :, :sq, :]
    dk = dk.reshape(b, h, skp, d)[:, :, :sk, :]
    dv = dv.reshape(b, h, skp, d)[:, :, :sk, :]
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _on_tpu():
    from paddle_tpu.ops.pallas import on_tpu
    return on_tpu()


def flash_attention_bshd(q, k, v, causal=False, scale=None, block_q=None,
                         block_k=None, interpret=None):
    """Flash attention on [batch, seq, heads, head_dim] inputs (paddle
    layout). Differentiable (custom VJP). Raises on CPU unless
    `interpret=True` — callers fall back to the XLA sdpa path."""
    if interpret is None:
        interpret = False
        if not _on_tpu():
            raise NotImplementedError(
                "pallas flash attention requires TPU (or interpret=True)")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    scale = float(scale)
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    o = _flash_bhsd(qt, kt, vt, bool(causal), scale,
                    block_q or DEFAULT_BLOCK_Q, block_k or DEFAULT_BLOCK_K,
                    bool(interpret))
    return jnp.transpose(o, (0, 2, 1, 3))


def flash_attention_bhsd(q, k, v, causal=False, scale=None, **kw):
    """Same kernel on [batch, heads, seq, head_dim] inputs."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kw.setdefault("interpret", not _on_tpu())
    return _flash_bhsd(q, k, v, bool(causal), float(scale),
                       kw.get("block_q") or DEFAULT_BLOCK_Q,
                       kw.get("block_k") or DEFAULT_BLOCK_K,
                       bool(kw["interpret"]))
