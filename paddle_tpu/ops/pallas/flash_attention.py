"""Flash attention for TPU (Pallas), forward + custom-VJP backward.

Reference parity: the reference exposes fused attention via
paddle.incubate.nn.functional.fused_attention / flash-attn CUDA kernels
(paddle/phi/kernels/gpu/flash_attn_kernel.cu in later branches). TPU-native
design: an online-softmax kernel tiled for the MXU — q blocks stream through
VMEM while k/v live in VMEM per (batch, head); fp32 accumulators; causal
blocks above the diagonal are skipped entirely (not masked), so causal
attention does ~half the FLOPs.

Layouts: public entry `flash_attention_bshd` takes paddle's [batch, seq,
heads, head_dim]; kernels run in [batch, heads, seq, head_dim].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU builds; interpret mode works without it
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _vmem_spec(*args, **kwargs):
    if _VMEM is not None:
        kwargs["memory_space"] = _VMEM
    return pl.BlockSpec(*args, **kwargs)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_k, seq_k_padded):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
    bq, d = q.shape

    num_kb = seq_k_padded // block_k
    if causal:
        # last k block whose start is <= this q block's end
        num_kb = jax.lax.min(num_kb, (qi + 1) * block_q // block_k +
                             (1 if block_q % block_k else 0))

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [bq,bk]
        col = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        mask = col < seq_k
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            mask = jnp.logical_and(mask, col <= row)
        s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l_safe))[:, 0]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   scale, causal, block_q, block_k, seq_k, seq_k_padded):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, None]
    delta = delta_ref[0][:, None]
    bq, d = q.shape

    num_kb = seq_k_padded // block_k
    if causal:
        num_kb = jax.lax.min(num_kb, (qi + 1) * block_q // block_k +
                             (1 if block_q % block_k else 0))

    def body(kb, dq):
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32)
        col = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        mask = col < seq_k
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            mask = jnp.logical_and(mask, col <= row)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)                                   # [bq,bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kb,
                           body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                    seq_q, seq_q_padded, seq_k):
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)                   # [bk, d]
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape

    num_qb = seq_q_padded // block_q
    start_qb = 0
    if causal:
        start_qb = ki * block_k // block_q

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q)][:, None]
        delta = delta_ref[0, pl.ds(qb * block_q, block_q)][:, None]
        s = scale * jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32)
        row = qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 0)
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 1)
        mask = jnp.logical_and(row < seq_q, col < seq_k)
        if causal:
            mask = jnp.logical_and(mask, col <= row)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)                                   # [bq?,bk]
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    z = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_qb, num_qb, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pad_to(x, mult, axis):
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, max(sq, 8))
    block_k = min(block_k, max(sk, 8))
    qp = _pad_to(q, block_q, 2)
    kp = _pad_to(k, block_k, 2)
    vp = _pad_to(v, block_k, 2)
    sqp, skp = qp.shape[2], kp.shape[2]
    qp = qp.reshape(b * h, sqp, d)
    kp = kp.reshape(b * h, skp, d)
    vp = vp.reshape(b * h, skp, d)

    grid = (b * h, sqp // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_k=sk, seq_k_padded=skp)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _vmem_spec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            _vmem_spec((1, skp, d), lambda bh, qi: (bh, 0, 0)),
            _vmem_spec((1, skp, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            _vmem_spec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            _vmem_spec((1, block_q), lambda bh, qi: (bh, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sqp, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sqp), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp)
    o = o.reshape(b, h, sqp, d)[:, :, :sq, :]
    lse = lse.reshape(b, h, sqp)[:, :, :sq]
    return o, lse


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                                    # [b,h,sq]
    return _flash_bwd_impl(q, k, v, do, lse, delta, causal, scale,
                           block_q, block_k, interpret)


def _flash_bwd_impl(q, k, v, do, lse, delta, causal, scale, block_q, block_k,
                    interpret):
    """dq/dk/dv given precomputed delta (= sum(do*o) for the plain kernel;
    ring attention folds the lse cotangent in as delta - dlse)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, max(sq, 8))
    block_k = min(block_k, max(sk, 8))

    qp = _pad_to(q, block_q, 2).reshape(b * h, -1, d)
    dop = _pad_to(do, block_q, 2).reshape(b * h, -1, d)
    lsep = _pad_to(lse, block_q, 2).reshape(b * h, -1)
    deltap = _pad_to(delta, block_q, 2).reshape(b * h, -1)
    kp = _pad_to(k, block_k, 2).reshape(b * h, -1, d)
    vp = _pad_to(v, block_k, 2).reshape(b * h, -1, d)
    sqp, skp = qp.shape[1], kp.shape[1]

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_k=sk, seq_k_padded=skp)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, sqp // block_q),
        in_specs=[
            _vmem_spec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            _vmem_spec((1, skp, d), lambda bh, qi: (bh, 0, 0)),
            _vmem_spec((1, skp, d), lambda bh, qi: (bh, 0, 0)),
            _vmem_spec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            _vmem_spec((1, block_q), lambda bh, qi: (bh, qi)),
            _vmem_spec((1, block_q), lambda bh, qi: (bh, qi)),
        ],
        out_specs=_vmem_spec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sqp, d), q.dtype),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_q=sq, seq_q_padded=sqp, seq_k=sk)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, skp // block_k),
        in_specs=[
            _vmem_spec((1, sqp, d), lambda bh, ki: (bh, 0, 0)),
            _vmem_spec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            _vmem_spec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            _vmem_spec((1, sqp, d), lambda bh, ki: (bh, 0, 0)),
            _vmem_spec((1, sqp), lambda bh, ki: (bh, 0)),
            _vmem_spec((1, sqp), lambda bh, ki: (bh, 0)),
        ],
        out_specs=[
            _vmem_spec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            _vmem_spec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, skp, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, skp, d), v.dtype),
        ],
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)

    dq = dq.reshape(b, h, sqp, d)[:, :, :sq, :]
    dk = dk.reshape(b, h, skp, d)[:, :, :sk, :]
    dv = dv.reshape(b, h, skp, d)[:, :, :sk, :]
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _on_tpu():
    from paddle_tpu.ops.pallas import on_tpu
    return on_tpu()


def flash_attention_bshd(q, k, v, causal=False, scale=None, block_q=None,
                         block_k=None, interpret=None):
    """Flash attention on [batch, seq, heads, head_dim] inputs (paddle
    layout). Differentiable (custom VJP). Raises on CPU unless
    `interpret=True` — callers fall back to the XLA sdpa path."""
    if interpret is None:
        interpret = False
        if not _on_tpu():
            raise NotImplementedError(
                "pallas flash attention requires TPU (or interpret=True)")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    scale = float(scale)
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    o = _flash_bhsd(qt, kt, vt, bool(causal), scale,
                    block_q or DEFAULT_BLOCK_Q, block_k or DEFAULT_BLOCK_K,
                    bool(interpret))
    return jnp.transpose(o, (0, 2, 1, 3))


def flash_attention_bhsd(q, k, v, causal=False, scale=None, **kw):
    """Same kernel on [batch, heads, seq, head_dim] inputs."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    kw.setdefault("interpret", not _on_tpu())
    return _flash_bhsd(q, k, v, bool(causal), float(scale),
                       kw.get("block_q") or DEFAULT_BLOCK_Q,
                       kw.get("block_k") or DEFAULT_BLOCK_K,
                       bool(kw["interpret"]))
