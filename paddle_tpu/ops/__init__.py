"""TPU kernel layer: Pallas kernels for the hot ops.

This package is the TPU-native replacement for the reference's C++ kernel
library (reference: paddle/phi/kernels/ — per-op CUDA kernels). Only the ops
where a hand-written kernel beats XLA fusion live here; everything else is
jnp/lax and left to XLA.
"""
