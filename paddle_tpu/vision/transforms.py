"""Vision transforms. Reference: python/paddle/vision/transforms/transforms.py.

Numpy/host-side preprocessing (runs in DataLoader workers, feeding the
device pipeline).
"""
from __future__ import annotations

import numbers

import numpy as np

from paddle_tpu.core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


def _to_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        img = _to_hwc(img).astype(np.float32)
        if img.dtype == np.uint8 or img.max() > 1.5:
            img = img / 255.0
        if self.data_format == "CHW":
            img = np.transpose(img, (2, 0, 1))
        return Tensor(img)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32).reshape(-1)
        self.std = np.asarray(std, np.float32).reshape(-1)
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            img = img.numpy()
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        out = (img - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, np.ndarray) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp
        arr = _to_hwc(img)
        method = {"nearest": "nearest", "bilinear": "linear",
                  "bicubic": "cubic"}.get(self.interpolation, "linear")
        out = jax.image.resize(jnp.asarray(arr, jnp.float32),
                               self.size + (arr.shape[2],), method=method)
        return np.asarray(out).astype(arr.dtype)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = _to_hwc(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_hwc(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else (self.padding,) * 4
            arr = np.pad(arr, [(p[1], p[3]), (p[0], p[2]), (0, 0)])
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return _to_hwc(img)[:, ::-1].copy()
        return _to_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return _to_hwc(img)[::-1].copy()
        return _to_hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        arr = _to_hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return self.resize._apply_image(arr[i:i + th, j:j + tw])
        return self.resize._apply_image(CenterCrop(min(h, w))._apply_image(arr))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return np.transpose(_to_hwc(img), self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.range = _jitter_range(value, "brightness")
        self.value = value

    def _apply_image(self, img):
        if self.range == (1.0, 1.0):
            return _to_hwc(img)
        return adjust_brightness(img, np.random.uniform(*self.range))


def _scale_clip(arr, out):
    """Clip to the input's value range, preserving uint8-ness."""
    if arr.dtype == np.uint8 or arr.max() > 1.5:
        return np.clip(out, 0, 255).astype(
            np.uint8 if arr.dtype == np.uint8 else arr.dtype)
    return np.clip(out, 0, 1).astype(arr.dtype)


def adjust_brightness(img, factor):
    arr = _to_hwc(img)
    return _scale_clip(arr, arr.astype(np.float32) * factor)


def adjust_contrast(img, factor):
    """Blend with the mean of the grayscale image (reference
    functional adjust_contrast semantics)."""
    arr = _to_hwc(img)
    f = arr.astype(np.float32)
    gray_mean = (f @ np.array([0.299, 0.587, 0.114], np.float32)).mean() \
        if arr.shape[2] == 3 else f.mean()
    return _scale_clip(arr, f * factor + gray_mean * (1.0 - factor))


def adjust_saturation(img, factor):
    arr = _to_hwc(img)
    f = arr.astype(np.float32)
    if arr.shape[2] != 3:
        return arr
    gray = (f @ np.array([0.299, 0.587, 0.114], np.float32))[..., None]
    return _scale_clip(arr, f * factor + gray * (1.0 - factor))


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns) through HSV space."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _to_hwc(img)
    if arr.shape[2] != 3:
        return arr
    scale = 255.0 if (arr.dtype == np.uint8 or arr.max() > 1.5) else 1.0
    f = arr.astype(np.float32) / scale
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    maxc = f.max(-1)
    minc = f.min(-1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-12), 0.0)
    dd = np.maximum(d, 1e-12)
    h = np.where(maxc == r, ((g - b) / dd) % 6.0,
                 np.where(maxc == g, (b - r) / dd + 2.0,
                          (r - g) / dd + 4.0))
    h = np.where(d == 0, 0.0, h) / 6.0
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    fr = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * fr)
    t = v * (1.0 - s * (1.0 - fr))
    i = i.astype(np.int32) % 6
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1) * scale
    return _scale_clip(arr, out)


def to_grayscale(img, num_output_channels=1):
    arr = _to_hwc(img)
    f = arr.astype(np.float32)
    gray = f @ np.array([0.299, 0.587, 0.114], np.float32) \
        if arr.shape[2] == 3 else f[..., 0]
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return _scale_clip(arr, out)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pt = pr = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    spec = [(pt, pb), (pl, pr), (0, 0)]
    if padding_mode == "constant":
        if isinstance(fill, (list, tuple)):  # per-channel fill (RGB)
            chans = [np.pad(arr[..., c:c + 1], spec[:2] + [(0, 0)],
                            mode="constant", constant_values=fill[c])
                     for c in range(arr.shape[2])]
            return np.concatenate(chans, axis=2)
        return np.pad(arr, spec, mode="constant", constant_values=fill)
    mode = {"edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    return np.pad(arr, spec, mode=mode)


def _inverse_warp(img, inv_matrix, interpolation="nearest", fill=0,
                  out_size=None):
    """Warp by sampling input at inv_matrix @ output-coords (3x3
    homography, pixel-center coordinates) — the shared core of rotate /
    affine / perspective."""
    arr = _to_hwc(img)
    h, w = arr.shape[:2]
    oh, ow = out_size or (h, w)
    ys, xs = np.meshgrid(np.arange(oh, dtype=np.float32),
                         np.arange(ow, dtype=np.float32), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1)
    src = np.asarray(inv_matrix, np.float32) @ coords
    sx = src[0] / np.maximum(np.abs(src[2]), 1e-12) * np.sign(src[2])
    sy = src[1] / np.maximum(np.abs(src[2]), 1e-12) * np.sign(src[2])
    f = arr.astype(np.float32)

    def sample(ix, iy):
        valid = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        ixc = np.clip(ix, 0, w - 1)
        iyc = np.clip(iy, 0, h - 1)
        out = f[iyc, ixc]
        out[~valid] = fill
        return out, valid

    if interpolation == "bilinear":
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        wx = (sx - x0)[:, None]
        wy = (sy - y0)[:, None]
        v00, _ = sample(x0, y0)
        v01, _ = sample(x0 + 1, y0)
        v10, _ = sample(x0, y0 + 1)
        v11, _ = sample(x0 + 1, y0 + 1)
        out = (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
               + v10 * (1 - wx) * wy + v11 * wx * wy)
        inside = (sx >= -0.5) & (sx <= w - 0.5) & (sy >= -0.5) & (sy <= h - 0.5)
        out[~inside] = fill
    else:
        out, _ = sample(np.round(sx).astype(np.int64),
                        np.round(sy).astype(np.int64))
    return _scale_clip(arr, out.reshape(oh, ow, arr.shape[2]))


def _affine_inv(angle_deg, translate, scale, shear_deg, center):
    """Inverse of the torchvision/paddle affine convention: output =
    T(center) T(translate) R(angle) Sh(shear) S(scale) T(-center) input."""
    a = np.deg2rad(angle_deg)
    sx, sy = np.deg2rad(shear_deg[0]), np.deg2rad(shear_deg[1])
    cx, cy = center
    tx, ty = translate
    # forward 2x3 (torchvision _get_inverse_affine_matrix, inverted there;
    # build forward then invert numerically for clarity)
    rot = np.array([[np.cos(a - sy) / np.cos(sy),
                     -np.cos(a - sy) * np.tan(sx) / np.cos(sy) - np.sin(a)],
                    [np.sin(a - sy) / np.cos(sy),
                     -np.sin(a - sy) * np.tan(sx) / np.cos(sy) + np.cos(a)]],
                   np.float32) * scale
    fwd = np.eye(3, dtype=np.float32)
    fwd[:2, :2] = rot
    pre = np.eye(3, dtype=np.float32)
    pre[:2, 2] = (-cx, -cy)
    post = np.eye(3, dtype=np.float32)
    post[:2, 2] = (cx + tx, cy + ty)
    return np.linalg.inv(post @ fwd @ pre).astype(np.float32)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr = _to_hwc(img)
    h, w = arr.shape[:2]
    c = center or ((w - 1) * 0.5, (h - 1) * 0.5)
    out_size = None
    if expand:
        a = np.deg2rad(angle)
        # tolerance before ceil: cos(90°) is ~6e-17, not 0, and
        # ceil(8 + 2e-16) would grow the canvas to 9
        ow = int(np.ceil(abs(w * np.cos(a)) + abs(h * np.sin(a)) - 1e-6))
        oh = int(np.ceil(abs(h * np.cos(a)) + abs(w * np.sin(a)) - 1e-6))
        out_size = (oh, ow)
        inv = _affine_inv(angle, ((ow - w) / 2, (oh - h) / 2), 1.0,
                          (0.0, 0.0), c)
    else:
        inv = _affine_inv(angle, (0, 0), 1.0, (0.0, 0.0), c)
    return _inverse_warp(arr, inv, interpolation, fill, out_size)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    arr = _to_hwc(img)
    h, w = arr.shape[:2]
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    c = center or ((w - 1) * 0.5, (h - 1) * 0.5)
    inv = _affine_inv(angle, tuple(translate), scale, tuple(shear), c)
    return _inverse_warp(arr, inv, interpolation, fill)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Warp so `startpoints` (4 corner [x, y]) map to `endpoints`."""
    arr = _to_hwc(img)
    a = []
    bvec = []
    for (sx, sy), (ex, ey) in zip(endpoints, startpoints):
        a.append([sx, sy, 1, 0, 0, 0, -ex * sx, -ex * sy])
        a.append([0, 0, 0, sx, sy, 1, -ey * sx, -ey * sy])
        bvec.extend([ex, ey])
    coeffs = np.linalg.lstsq(np.asarray(a, np.float32),
                             np.asarray(bvec, np.float32), rcond=None)[0]
    inv = np.append(coeffs, 1.0).reshape(3, 3).astype(np.float32)
    return _inverse_warp(arr, inv, interpolation, fill)


def _looks_chw(arr):
    return (arr.ndim == 3 and arr.shape[0] in (1, 3)
            and arr.shape[2] not in (1, 3))


def erase(img, i, j, h, w, v, inplace=False):
    """Zero/fill a region (reference functional erase); works on Tensor,
    HWC ndarray or CHW ndarray (layout detected for both)."""
    if isinstance(img, Tensor):
        arr = img.numpy().copy()
        if _looks_chw(arr):
            arr[:, i:i + h, j:j + w] = v
        else:
            arr[i:i + h, j:j + w] = v
        return Tensor(arr)
    arr = np.asarray(img) if inplace else np.array(img)
    if _looks_chw(arr):
        arr[:, i:i + h, j:j + w] = v        # CHW
    else:
        arr[i:i + h, j:j + w] = v           # HWC
    return arr


def _jitter_range(value, name, center=1.0, bound=None):
    """paddle ColorJitter args are float-or-(min,max): a float v means
    [max(0, center-v), center+v]; a pair is used as-is."""
    if isinstance(value, (list, tuple)):
        lo, hi = float(value[0]), float(value[1])
    else:
        if value < 0:
            raise ValueError(f"{name} value should be non-negative")
        if bound is not None and value > bound:
            raise ValueError(f"{name} value should be in [0, {bound}]")
        lo, hi = max(0.0, center - value), center + value
        if center == 0.0:
            lo = -value
    return lo, hi


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.range = _jitter_range(value, "contrast")
        self.value = value

    def _apply_image(self, img):
        if self.range == (1.0, 1.0):
            return _to_hwc(img)
        return adjust_contrast(img, np.random.uniform(*self.range))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.range = _jitter_range(value, "saturation")
        self.value = value

    def _apply_image(self, img):
        if self.range == (1.0, 1.0):
            return _to_hwc(img)
        return adjust_saturation(img, np.random.uniform(*self.range))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.range = _jitter_range(value, "hue", center=0.0, bound=0.5)
        self.value = value

    def _apply_image(self, img):
        if self.range == (0.0, 0.0):
            return _to_hwc(img)
        return adjust_hue(img, np.random.uniform(*self.range))


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            degrees = (-degrees, degrees)
        self.degrees = tuple(degrees)
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = _to_hwc(img)
        h, w = arr.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        scale = np.random.uniform(*self.scale) if self.scale else 1.0
        sx = sy = 0.0
        if self.shear is not None:
            sh = self.shear
            if isinstance(sh, numbers.Number):
                sh = (-sh, sh)
            sx = np.random.uniform(sh[0], sh[1])
            if len(sh) == 4:
                sy = np.random.uniform(sh[2], sh[3])
        return affine(arr, angle, (tx, ty), scale, (sx, sy),
                      self.interpolation, self.fill, self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        arr = _to_hwc(img)
        if np.random.rand() >= self.prob:
            return arr
        h, w = arr.shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)
        start = [[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]]
        end = [[np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)],
               [w - 1 - np.random.randint(0, dx + 1),
                np.random.randint(0, dy + 1)],
               [w - 1 - np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1)],
               [np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1)]]
        return perspective(arr, start, end, self.interpolation, self.fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        chw = _looks_chw(arr)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                v = self.value if isinstance(self.value, numbers.Number) \
                    else np.asarray(self.value).reshape(
                        (-1, 1, 1) if chw else (1, 1, -1))
                return erase(img, i, j, eh, ew, v, self.inplace)
        return img


class ColorJitter(BaseTransform):
    """Randomly-ordered brightness/contrast/saturation/hue jitter
    (reference transforms.py ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def _apply_image(self, img):
        ops = []
        if self.brightness:
            ops.append(BrightnessTransform(self.brightness))
        if self.contrast:
            ops.append(ContrastTransform(self.contrast))
        if self.saturation:
            ops.append(SaturationTransform(self.saturation))
        if self.hue:
            ops.append(HueTransform(self.hue))
        np.random.shuffle(ops)
        out = _to_hwc(img)
        for op in ops:
            out = op._apply_image(out)
        return out


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format, to_rgb)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _to_hwc(img)[:, ::-1].copy()


def vflip(img):
    return _to_hwc(img)[::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    return _to_hwc(img)[top:top + height, left:left + width]


class FusedImageAugment:
    """Batch-level fused augmentation on the native C++ pipeline
    (paddle_tpu/native ptdata_augment_batch): zero-pad -> random crop ->
    random hflip -> /255 -> normalize -> float32 CHW/HWC in ONE GIL-free
    threaded pass. The per-sample transform chain (RandomCrop +
    RandomHorizontalFlip + Normalize + ToTensor) costs a Python call per
    image per stage; this is the whole chain per BATCH. Training-style
    randomness is deterministic per (seed, epoch, sample index).

    Apply to uint8 [N, H, W, C] batches (e.g. as DataLoader batch-level
    preprocessing before host->device transfer).
    """

    def __init__(self, size, pad=0, random_crop=True, random_flip=True,
                 mean=0.0, std=1.0, data_format="CHW", seed=0):
        self.size = size
        self.pad = pad
        self.random_crop = random_crop
        self.random_flip = random_flip
        self.mean = mean
        self.std = std
        self.to_chw = data_format.upper() == "CHW"
        self.seed = seed
        self._epoch = 0
        self._batch = 0

    def set_epoch(self, epoch):
        self._epoch = int(epoch)
        self._batch = 0

    def __call__(self, batch):
        from paddle_tpu import native
        import numpy as _np
        arr = _np.asarray(batch)
        # fold (seed, epoch, batch counter) so every batch draws a fresh
        # stream — without the counter each epoch would reuse the same
        # batch_size augmentations for every batch
        mix = (self.seed * 1000003 + self._epoch) * 2654435761             + self._batch
        self._batch += 1
        return native.augment_batch(
            arr, self.size, pad=self.pad, random_crop=self.random_crop,
            random_flip=self.random_flip, mean=self.mean, std=self.std,
            to_chw=self.to_chw, seed=mix & 0xFFFFFFFFFFFF)
