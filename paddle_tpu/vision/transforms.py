"""Vision transforms. Reference: python/paddle/vision/transforms/transforms.py.

Numpy/host-side preprocessing (runs in DataLoader workers, feeding the
device pipeline).
"""
from __future__ import annotations

import numbers

import numpy as np

from paddle_tpu.core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


def _to_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        img = _to_hwc(img).astype(np.float32)
        if img.dtype == np.uint8 or img.max() > 1.5:
            img = img / 255.0
        if self.data_format == "CHW":
            img = np.transpose(img, (2, 0, 1))
        return Tensor(img)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32).reshape(-1)
        self.std = np.asarray(std, np.float32).reshape(-1)
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            img = img.numpy()
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        out = (img - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, np.ndarray) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        import jax
        import jax.numpy as jnp
        arr = _to_hwc(img)
        method = {"nearest": "nearest", "bilinear": "linear",
                  "bicubic": "cubic"}.get(self.interpolation, "linear")
        out = jax.image.resize(jnp.asarray(arr, jnp.float32),
                               self.size + (arr.shape[2],), method=method)
        return np.asarray(out).astype(arr.dtype)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = _to_hwc(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_hwc(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else (self.padding,) * 4
            arr = np.pad(arr, [(p[1], p[3]), (p[0], p[2]), (0, 0)])
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return _to_hwc(img)[:, ::-1].copy()
        return _to_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return _to_hwc(img)[::-1].copy()
        return _to_hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        arr = _to_hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return self.resize._apply_image(arr[i:i + th, j:j + tw])
        return self.resize._apply_image(CenterCrop(min(h, w))._apply_image(arr))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return np.transpose(_to_hwc(img), self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _to_hwc(img).astype(np.float32)
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(arr * factor, 0, 255).astype(np.uint8) \
            if arr.max() > 1.5 else np.clip(arr * factor, 0, 1)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.brightness = brightness

    def _apply_image(self, img):
        if self.brightness:
            return BrightnessTransform(self.brightness)._apply_image(img)
        return _to_hwc(img)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format, to_rgb)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _to_hwc(img)[:, ::-1].copy()


def vflip(img):
    return _to_hwc(img)[::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    return _to_hwc(img)[top:top + height, left:left + width]


class FusedImageAugment:
    """Batch-level fused augmentation on the native C++ pipeline
    (paddle_tpu/native ptdata_augment_batch): zero-pad -> random crop ->
    random hflip -> /255 -> normalize -> float32 CHW/HWC in ONE GIL-free
    threaded pass. The per-sample transform chain (RandomCrop +
    RandomHorizontalFlip + Normalize + ToTensor) costs a Python call per
    image per stage; this is the whole chain per BATCH. Training-style
    randomness is deterministic per (seed, epoch, sample index).

    Apply to uint8 [N, H, W, C] batches (e.g. as DataLoader batch-level
    preprocessing before host->device transfer).
    """

    def __init__(self, size, pad=0, random_crop=True, random_flip=True,
                 mean=0.0, std=1.0, data_format="CHW", seed=0):
        self.size = size
        self.pad = pad
        self.random_crop = random_crop
        self.random_flip = random_flip
        self.mean = mean
        self.std = std
        self.to_chw = data_format.upper() == "CHW"
        self.seed = seed
        self._epoch = 0
        self._batch = 0

    def set_epoch(self, epoch):
        self._epoch = int(epoch)
        self._batch = 0

    def __call__(self, batch):
        from paddle_tpu import native
        import numpy as _np
        arr = _np.asarray(batch)
        # fold (seed, epoch, batch counter) so every batch draws a fresh
        # stream — without the counter each epoch would reuse the same
        # batch_size augmentations for every batch
        mix = (self.seed * 1000003 + self._epoch) * 2654435761             + self._batch
        self._batch += 1
        return native.augment_batch(
            arr, self.size, pad=self.pad, random_crop=self.random_crop,
            random_flip=self.random_flip, mean=self.mean, std=self.std,
            to_chw=self.to_chw, seed=mix & 0xFFFFFFFFFFFF)
