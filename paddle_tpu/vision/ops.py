"""Detection / geometry vision ops.

Reference parity: python/paddle/vision/ops.py — yolo_box (:283),
deform_conv2d (:850) + DeformConv2D (:1088), psroi_pool (:1545) +
PSRoIPool (:1632), roi_pool (:1677) + RoIPool (:1771), roi_align (:1818)
+ RoIAlign (:1959), nms (:2064), ConvNormActivation (:2007); numeric
semantics match the phi CPU kernels (paddle/phi/kernels/cpu/
{yolo_box,psroi_pool,roi_pool,roi_align,deformable_conv}_kernel.cc).

TPU-native design: the reference implements these as per-element CUDA/C++
loops; here every op is a dense, statically-shaped jnp computation —
masked-sum einsums for the pooling ops (the variable-extent bins of the
scalar kernels become bin-membership weight masks contracted on the MXU),
vectorized bilinear gathers for roi_align / deform_conv2d, and a
lax.fori_loop suppression sweep for nms. All ops differentiate through
the standard JAX AD rules (the reference's hand-written grad kernels come
for free).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

__all__ = [
    "yolo_box", "deform_conv2d", "DeformConv2D", "psroi_pool", "PSRoIPool",
    "roi_pool", "RoIPool", "roi_align", "RoIAlign", "nms",
    "ConvNormActivation",
]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# yolo_box
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head output into boxes + scores.

    x: [N, C, H, W] with C = S*(5+class_num) (S anchors), or S*(6+class_num)
    when iou_aware. img_size: [N, 2] (h, w). Returns (boxes [N, S*H*W, 4]
    xyxy in image scale, scores [N, S*H*W, class_num]); rows whose
    conf*<=conf_thresh have zero scores, matching the phi kernel.
    """
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)  # (S, [w,h])
    S = anchors.shape[0]

    def fn(xv, img):
        N, C, H, W = xv.shape
        attrs = C // S
        xv = xv.reshape(N, S, attrs, H, W)
        if iou_aware:
            iou_pred = jax.nn.sigmoid(xv[:, :, 0])           # [N,S,H,W]
            xv = xv[:, :, 1:]
        grid_x = jnp.arange(W, dtype=jnp.float32)
        grid_y = jnp.arange(H, dtype=jnp.float32)
        sx = float(scale_x_y)
        bias = -0.5 * (sx - 1.0)
        bx = (jax.nn.sigmoid(xv[:, :, 0]) * sx + bias + grid_x) / W
        by = (jax.nn.sigmoid(xv[:, :, 1]) * sx + bias
              + grid_y[:, None]) / H
        in_w = float(downsample_ratio) * W
        in_h = float(downsample_ratio) * H
        pw = anchors[:, 0][None, :, None, None] / in_w
        ph = anchors[:, 1][None, :, None, None] / in_h
        bw = jnp.exp(xv[:, :, 2]) * pw
        bh = jnp.exp(xv[:, :, 3]) * ph
        conf = jax.nn.sigmoid(xv[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) * \
                iou_pred ** iou_aware_factor
        cls = jax.nn.sigmoid(xv[:, :, 5:])                   # [N,S,cn,H,W]

        imgh = img[:, 0].astype(jnp.float32)[:, None, None, None]
        imgw = img[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imgw
        y1 = (by - bh / 2) * imgh
        x2 = (bx + bw / 2) * imgw
        y2 = (by + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, imgw - 1.0)
            y1 = jnp.clip(y1, 0.0, imgh - 1.0)
            x2 = jnp.clip(x2, 0.0, imgw - 1.0)
            y2 = jnp.clip(y2, 0.0, imgh - 1.0)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)          # [N,S,H,W,4]
        # phi kernel: anchors with conf < conf_thresh emit all-zero box
        # AND score rows (downstream consumers use zero boxes as the drop
        # marker); conf == thresh is kept
        keep = conf >= conf_thresh                            # [N,S,H,W]
        boxes = jnp.where(keep[..., None], boxes, 0.0)
        sc = conf[:, :, None] * cls                           # [N,S,cn,H,W]
        sc = jnp.where(keep[:, :, None], sc, 0.0)
        boxes = boxes.reshape(N, S * H * W, 4)
        sc = jnp.moveaxis(sc, 2, -1).reshape(N, S * H * W, class_num)
        return boxes, sc

    out = apply(fn, x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)),
                img_size if isinstance(img_size, Tensor)
                else Tensor(jnp.asarray(img_size)))
    return out


# ---------------------------------------------------------------------------
# Bilinear sampling helper (roi_align, deform_conv2d)
# ---------------------------------------------------------------------------

def _bilinear_gather(feat, ys, xs):
    """Sample feat [C, H, W] at fractional (ys, xs) [...]; zero outside
    [-1, H] x [-1, W] (phi kernels' boundary convention). Returns
    [C, ...]."""
    H, W = feat.shape[-2:]
    valid = (ys > -1.0) & (ys < H) & (xs > -1.0) & (xs < W)
    y = jnp.clip(ys, 0.0, H - 1.0)
    x = jnp.clip(xs, 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly = y - y0
    lx = x - x0
    hy = 1.0 - ly
    hx = 1.0 - lx
    v00 = feat[:, y0, x0]
    v01 = feat[:, y0, x1]
    v10 = feat[:, y1, x0]
    v11 = feat[:, y1, x1]
    out = (v00 * (hy * hx) + v01 * (hy * lx)
           + v10 * (ly * hx) + v11 * (ly * lx))
    return jnp.where(valid, out, 0.0)


def _batch_ids(boxes_num, num_rois):
    """Expand per-image box counts into a per-roi batch index (host-side:
    counts define static gather shapes, mirroring the phi rois_num path)."""
    counts = np.asarray(boxes_num, np.int64)
    return np.repeat(np.arange(len(counts)), counts).astype(np.int32)


# ---------------------------------------------------------------------------
# roi_align
# ---------------------------------------------------------------------------

def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (Mask R-CNN). boxes [R, 4] xyxy; boxes_num [N] per-image
    counts. Returns [R, C, ph, pw]. sampling_ratio <= 0 uses the adaptive
    ceil(bin) count, resolved on host from the (eager) box values —
    pass a positive sampling_ratio for fully-traced use."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bv = _val(boxes)
    bids = _batch_ids(np.asarray(_val(boxes_num)), bv.shape[0])

    def one_roi(feat, box, sh, sw):
        """Pool one roi from feat [C, H, W] with an sh x sw sample grid
        per bin (sh/sw static)."""
        off = 0.5 if aligned else 0.0
        bx = box * spatial_scale
        x1, y1 = bx[0] - off, bx[1] - off
        rw = bx[2] - bx[0]
        rh = bx[3] - bx[1]
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        ys = y1 + (jnp.arange(ph)[:, None]
                   + (jnp.arange(sh) + 0.5)[None, :] / sh) * bin_h  # [ph,sh]
        xs = x1 + (jnp.arange(pw)[:, None]
                   + (jnp.arange(sw) + 0.5)[None, :] / sw) * bin_w  # [pw,sw]
        yy = jnp.broadcast_to(ys[:, :, None, None], (ph, sh, pw, sw))
        xx = jnp.broadcast_to(xs[None, None, :, :], (ph, sh, pw, sw))
        vals = _bilinear_gather(feat, yy, xx)        # [C, ph, sh, pw, sw]
        return vals.mean(axis=(2, 4))                # [C, ph, pw]

    if sampling_ratio > 0:
        s = int(sampling_ratio)

        def fn(xv, bv):
            feats = xv[jnp.asarray(bids)]            # [R, C, H, W]
            return jax.vmap(lambda f, b: one_roi(f, b, s, s))(feats, bv)

        return apply(fn, x if isinstance(x, Tensor)
                     else Tensor(jnp.asarray(x)),
                     boxes if isinstance(boxes, Tensor)
                     else Tensor(jnp.asarray(boxes)))

    # adaptive (reference default): per-roi ceil(bin) sample counts are
    # data-dependent → resolved on host per roi (eager path; pass a
    # positive sampling_ratio for fully-traced use)
    b_host = np.asarray(jax.device_get(bv), np.float32)
    rw = (b_host[:, 2] - b_host[:, 0]) * spatial_scale
    rh = (b_host[:, 3] - b_host[:, 1]) * spatial_scale
    if not aligned:
        rw = np.maximum(rw, 1.0)
        rh = np.maximum(rh, 1.0)
    shs = np.maximum(np.ceil(rh / ph), 1).astype(int)
    sws = np.maximum(np.ceil(rw / pw), 1).astype(int)

    def fn(xv, bv):
        outs = []
        for r in range(bv.shape[0]):
            outs.append(one_roi(xv[int(bids[r])], bv[r],
                                int(shs[r]), int(sws[r])))
        return jnp.stack(outs, 0) if outs else \
            jnp.zeros((0, xv.shape[1], ph, pw), xv.dtype)

    return apply(fn, x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)),
                 boxes if isinstance(boxes, Tensor)
                 else Tensor(jnp.asarray(boxes)))


# ---------------------------------------------------------------------------
# roi_pool / psroi_pool — masked-sum einsum formulation
# ---------------------------------------------------------------------------

def _bin_masks(starts, ends, size):
    """Membership mask [..., size] of positions i with start <= i < end."""
    idx = jnp.arange(size, dtype=jnp.float32)
    return ((idx >= starts[..., None]) & (idx < ends[..., None])) \
        .astype(jnp.float32)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoI max pooling (Fast R-CNN). Quantized-bin max, phi rounding:
    start = round(coord * scale), bins floored/ceiled; empty bins -> 0."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bids = _batch_ids(np.asarray(_val(boxes_num)), _val(boxes).shape[0])

    def fn(xv, bv):
        N, C, H, W = xv.shape
        r0 = jnp.round(bv * spatial_scale)
        x1, y1, x2, y2 = r0[:, 0], r0[:, 1], r0[:, 2], r0[:, 3]
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        phi_ = jnp.arange(ph, dtype=jnp.float32)
        pwi = jnp.arange(pw, dtype=jnp.float32)
        hs = jnp.clip(jnp.floor(phi_[None] * bin_h[:, None]) + y1[:, None],
                      0, H)
        he = jnp.clip(jnp.ceil((phi_[None] + 1) * bin_h[:, None])
                      + y1[:, None], 0, H)
        ws = jnp.clip(jnp.floor(pwi[None] * bin_w[:, None]) + x1[:, None],
                      0, W)
        we = jnp.clip(jnp.ceil((pwi[None] + 1) * bin_w[:, None])
                      + x1[:, None], 0, W)
        mh = _bin_masks(hs, he, H)                            # [R, ph, H]
        mw = _bin_masks(ws, we, W)                            # [R, pw, W]
        feats = xv[jnp.asarray(bids)]                         # [R, C, H, W]
        neg = jnp.finfo(jnp.float32).min
        # one masked reduction per output bin, reusing the [R,C,H,W]
        # feature gather — a dense [R,C,ph,pw,H,W] broadcast would be
        # tens of GB at detection sizes
        rows = []
        for i in range(ph):
            cols = []
            for j in range(pw):
                m = mh[:, i, :, None] * mw[:, j, None, :]     # [R, H, W]
                v = jnp.where(m[:, None] > 0, feats, neg).max((-2, -1))
                cols.append(v)                                # [R, C]
            rows.append(jnp.stack(cols, -1))                  # [R, C, pw]
        out = jnp.stack(rows, -2)                             # [R,C,ph,pw]
        empty = (mh.sum(-1)[:, :, None] * mw.sum(-1)[:, None, :]) == 0
        return jnp.where(empty[:, None], 0.0, out)

    return apply(fn, x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)),
                 boxes if isinstance(boxes, Tensor)
                 else Tensor(jnp.asarray(boxes)))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (R-FCN). Input channels must
    equal out_channels * ph * pw; each output bin (c, ph, pw) averages its
    own input channel over the bin extent (phi rounding: round(coord),
    end+1, min-size 0.1)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bids = _batch_ids(np.asarray(_val(boxes_num)), _val(boxes).shape[0])

    def fn(xv, bv):
        N, C, H, W = xv.shape
        if C % (ph * pw):
            raise ValueError(
                "psroi_pool: input channels must be a multiple of "
                f"output_size h*w, got {C} vs {ph}x{pw}")
        c_out = C // (ph * pw)
        rs = jnp.round(bv)
        y1 = rs[:, 1] * spatial_scale
        x1 = rs[:, 0] * spatial_scale
        y2 = (rs[:, 3] + 1.0) * spatial_scale
        x2 = (rs[:, 2] + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h = rh / ph
        bin_w = rw / pw
        phi_ = jnp.arange(ph, dtype=jnp.float32)
        pwi = jnp.arange(pw, dtype=jnp.float32)
        hs = jnp.clip(jnp.floor(phi_[None] * bin_h[:, None] + y1[:, None]),
                      0, H)
        he = jnp.clip(jnp.ceil((phi_[None] + 1) * bin_h[:, None]
                               + y1[:, None]), 0, H)
        ws = jnp.clip(jnp.floor(pwi[None] * bin_w[:, None] + x1[:, None]),
                      0, W)
        we = jnp.clip(jnp.ceil((pwi[None] + 1) * bin_w[:, None]
                               + x1[:, None]), 0, W)
        mh = _bin_masks(hs, he, H)                            # [R, ph, H]
        mw = _bin_masks(ws, we, W)                            # [R, pw, W]
        feats = xv[jnp.asarray(bids)]                         # [R, C, H, W]
        feats = feats.reshape(feats.shape[0], c_out, ph, pw, H, W)
        # masked sum contracted on the MXU: bin membership is a weight mask
        s = jnp.einsum("rcpqhw,rph,rqw->rcpq", feats, mh, mw)
        area = mh.sum(-1)[:, :, None] * mw.sum(-1)[:, None, :]  # [R,ph,pw]
        return jnp.where(area[:, None] > 0, s / jnp.maximum(area[:, None],
                                                            1.0), 0.0)

    return apply(fn, x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)),
                 boxes if isinstance(boxes, Tensor)
                 else Tensor(jnp.asarray(boxes)))


# ---------------------------------------------------------------------------
# deform_conv2d (DCNv1/v2)
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution. offset: [N, 2*dg*kh*kw, Hout, Wout] with
    channel pairs (dy, dx) per kernel tap (phi deformable_conv_functor
    layout); mask (DCNv2): [N, dg*kh*kw, Hout, Wout] multiplies the
    bilinear-sampled value. weight: [Cout, Cin/groups, kh, kw]."""
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def fn(xv, ov, wv, bv, mv):
        N, Cin, H, W = xv.shape
        Cout, _, kh, kw = wv.shape
        Ho, Wo = ov.shape[2], ov.shape[3]
        dg = deformable_groups
        K = kh * kw
        ov = ov.reshape(N, dg, K, 2, Ho, Wo)
        base_y = jnp.arange(Ho, dtype=jnp.float32) * stride[0] - padding[0]
        base_x = jnp.arange(Wo, dtype=jnp.float32) * stride[1] - padding[1]
        tap_y = (jnp.arange(K) // kw).astype(jnp.float32) * dilation[0]
        tap_x = (jnp.arange(K) % kw).astype(jnp.float32) * dilation[1]
        # unperturbed sample grid per kernel tap: [K, Ho, Wo]
        sample_y = tap_y[:, None, None] + base_y[None, :, None] \
            + jnp.zeros((1, 1, Wo))
        sample_x = tap_x[:, None, None] + base_x[None, None, :] \
            + jnp.zeros((1, Ho, 1))

        def per_image(feat, off_i, mask_i):
            # feat [Cin, H, W]; off_i [dg, K, 2, Ho, Wo]
            yy = sample_y[None] + off_i[:, :, 0]              # [dg,K,Ho,Wo]
            xx = sample_x[None] + off_i[:, :, 1]
            featg = feat.reshape(dg, Cin // dg, H, W)
            vals = jax.vmap(_bilinear_gather)(featg, yy, xx)  # [dg,cpg,K,..]
            if mask_i is not None:
                vals = vals * mask_i[:, None]
            return vals.reshape(Cin, K, Ho, Wo)

        if mv is not None:
            mvr = mv.reshape(N, dg, K, Ho, Wo)
            cols = jax.vmap(per_image)(xv, ov, mvr)
        else:
            cols = jax.vmap(lambda f, o: per_image(f, o, None))(xv, ov)
        # cols: [N, Cin, K, Ho, Wo]; contract with weight on the MXU
        cpg = Cin // groups
        opg = Cout // groups
        colsg = cols.reshape(N, groups, cpg, K, Ho, Wo)
        wg = wv.reshape(groups, opg, cpg, kh * kw)
        out = jnp.einsum("ngckhw,gock->ngohw", colsg, wg)
        out = out.reshape(N, Cout, Ho, Wo)
        if bv is not None:
            out = out + bv[None, :, None, None]
        return out

    args = [x, offset, weight]
    tensors = [a if isinstance(a, Tensor) else Tensor(jnp.asarray(a))
               for a in args]
    b_t = bias if bias is None or isinstance(bias, Tensor) \
        else Tensor(jnp.asarray(bias))
    m_t = mask if mask is None or isinstance(mask, Tensor) \
        else Tensor(jnp.asarray(mask))
    if b_t is not None and m_t is not None:
        return apply(fn, *tensors, b_t, m_t)
    if b_t is not None:
        return apply(lambda xv, ov, wv, bv: fn(xv, ov, wv, bv, None),
                     *tensors, b_t)
    if m_t is not None:
        return apply(lambda xv, ov, wv, mv: fn(xv, ov, wv, None, mv),
                     *tensors, m_t)
    return apply(lambda xv, ov, wv: fn(xv, ov, wv, None, None), *tensors)


class DeformConv2D(Layer):
    """Layer wrapper over deform_conv2d (reference vision/ops.py:1088)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, *ks],
            attr=weight_attr, default_initializer=I.XavierUniform())
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self._stride, self._padding, self._dilation,
                             self._deformable_groups, self._groups, mask)


# ---------------------------------------------------------------------------
# nms
# ---------------------------------------------------------------------------

def _box_iou_matrix(b):
    """Pairwise IoU of [R, 4] xyxy boxes (area convention of the phi nms
    kernel: plain (x2-x1)*(y2-y1))."""
    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _nms_keep_mask(boxes_sorted, iou_threshold):
    """Greedy suppression over pre-sorted boxes; returns bool keep mask.
    Device-side O(R²) sweep (one fori_loop over rows)."""
    iou = _box_iou_matrix(boxes_sorted)
    R = boxes_sorted.shape[0]

    def body(i, keep):
        sup = (iou[i] > iou_threshold) & (jnp.arange(R) > i) & keep[i]
        return keep & ~sup

    return lax.fori_loop(0, R, body, jnp.ones((R,), bool))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Reference vision/ops.py:2064. Greedy NMS; with scores, boxes are
    ranked by score first; with categories, NMS runs per category and
    results merge score-sorted; top_k truncates. Returns kept indices
    (int64, host-materialized — output size is data-dependent)."""
    bv = _val(boxes)
    if scores is None:
        keep = np.asarray(jax.device_get(_nms_keep_mask(bv, iou_threshold)))
        return Tensor(jnp.asarray(np.nonzero(keep)[0].astype(np.int64)))

    sv = _val(scores)
    if category_idxs is None:
        order = jnp.argsort(-sv)
        keep = _nms_keep_mask(bv[order], iou_threshold)
        keep_np = np.asarray(jax.device_get(keep))
        order_np = np.asarray(jax.device_get(order))
        out = order_np[np.nonzero(keep_np)[0]]
        if top_k is not None:
            out = out[:top_k]
        return Tensor(jnp.asarray(out.astype(np.int64)))

    assert categories is not None, \
        "categories is required when category_idxs is given"
    cv = np.asarray(jax.device_get(_val(category_idxs)))
    sv_np = np.asarray(jax.device_get(sv))
    kept = []
    for cat in categories:
        idxs = np.nonzero(cv == cat)[0]
        if idxs.size == 0:
            continue
        if idxs.size == 1:
            kept.append(idxs)
            continue
        order = idxs[np.argsort(-sv_np[idxs], kind="stable")]
        keep = np.asarray(jax.device_get(
            _nms_keep_mask(bv[jnp.asarray(order)], iou_threshold)))
        kept.append(order[keep])
    if kept:
        kept = np.concatenate(kept)
    else:
        kept = np.zeros((0,), np.int64)
    kept = kept[np.argsort(-sv_np[kept], kind="stable")]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept.astype(np.int64)))


# ---------------------------------------------------------------------------
# ConvNormActivation
# ---------------------------------------------------------------------------

_DEFAULT = object()


def ConvNormActivation(in_channels, out_channels, kernel_size=3, stride=1,
                       padding=None, groups=1, norm_layer=_DEFAULT,
                       activation_layer=_DEFAULT, dilation=1, bias=None):
    """Conv2D + norm + activation block (reference vision/ops.py:2007).
    norm_layer/activation_layer default to BatchNorm2D/ReLU; passing None
    explicitly SKIPS that stage (and a skipped norm enables the conv
    bias), matching the reference semantics."""
    from paddle_tpu.nn import BatchNorm2D, Conv2D, ReLU, Sequential

    if padding is None:
        padding = (kernel_size - 1) // 2 * dilation
    if norm_layer is _DEFAULT:
        norm_layer = BatchNorm2D
    if activation_layer is _DEFAULT:
        activation_layer = ReLU
    if bias is None:
        bias = norm_layer is None
    layers = [Conv2D(in_channels, out_channels, kernel_size, stride,
                     padding, dilation=dilation, groups=groups,
                     bias_attr=None if bias else False)]
    if norm_layer is not None:
        layers.append(norm_layer(out_channels))
    if activation_layer is not None:
        layers.append(activation_layer())
    return Sequential(*layers)


class PSRoIPool(Layer):
    """Reference vision/ops.py:1632."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


class RoIPool(Layer):
    """Reference vision/ops.py:1771."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class RoIAlign(Layer):
    """Reference vision/ops.py:1959."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)
