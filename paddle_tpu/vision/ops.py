"""Detection / geometry vision ops.

Reference parity: python/paddle/vision/ops.py — yolo_box (:283),
deform_conv2d (:850) + DeformConv2D (:1088), psroi_pool (:1545) +
PSRoIPool (:1632), roi_pool (:1677) + RoIPool (:1771), roi_align (:1818)
+ RoIAlign (:1959), nms (:2064), ConvNormActivation (:2007); numeric
semantics match the phi CPU kernels (paddle/phi/kernels/cpu/
{yolo_box,psroi_pool,roi_pool,roi_align,deformable_conv}_kernel.cc).

TPU-native design: the reference implements these as per-element CUDA/C++
loops; here every op is a dense, statically-shaped jnp computation —
masked-sum einsums for the pooling ops (the variable-extent bins of the
scalar kernels become bin-membership weight masks contracted on the MXU),
vectorized bilinear gathers for roi_align / deform_conv2d, and a
lax.fori_loop suppression sweep for nms. All ops differentiate through
the standard JAX AD rules (the reference's hand-written grad kernels come
for free).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

__all__ = [
    "yolo_box", "deform_conv2d", "DeformConv2D", "psroi_pool", "PSRoIPool",
    "roi_pool", "RoIPool", "roi_align", "RoIAlign", "nms",
    "ConvNormActivation", "box_coder", "prior_box", "matrix_nms",
    "distribute_fpn_proposals", "yolo_loss", "generate_proposals",
    "read_file", "decode_jpeg",
]


def read_file(filename, name=None):
    """File bytes as a 1-D uint8 Tensor (reference vision/ops.py:1448).

    Host-side IO: the bytes stay in host memory (a cpu-device array) —
    only decode_jpeg's output (the pixel array) should ever move to the
    accelerator, so the compressed file never does a device round-trip
    on the data-loading path.
    """
    import jax
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return Tensor(jax.device_put(data, jax.devices("cpu")[0]))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode JPEG bytes into a CHW uint8 image tensor (reference
    vision/ops.py:1493; the phi kernel wraps nvjpeg — here decoding is
    host-side PIL, which is where decode belongs on a TPU system).

    mode: 'unchanged' (keep the file's channel count), 'gray', or 'rgb'.
    """
    import io as _io

    import jax.numpy as _jnp
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("decode_jpeg needs PIL (pillow)") from e
    raw = bytes(np.asarray(_val(x), dtype=np.uint8).tobytes())
    with Image.open(_io.BytesIO(raw)) as img:
        if mode == "gray":
            img = img.convert("L")
        elif mode in ("rgb", "RGB"):
            img = img.convert("RGB")
        elif mode != "unchanged":
            raise ValueError(f"decode_jpeg: unknown mode {mode!r}")
        arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]                      # [1, H, W]
    else:
        arr = np.transpose(arr, (2, 0, 1))   # HWC -> CHW
    return Tensor(_jnp.asarray(arr))


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# yolo_box
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head output into boxes + scores.

    x: [N, C, H, W] with C = S*(5+class_num) (S anchors), or S*(6+class_num)
    when iou_aware. img_size: [N, 2] (h, w). Returns (boxes [N, S*H*W, 4]
    xyxy in image scale, scores [N, S*H*W, class_num]); rows whose
    conf*<=conf_thresh have zero scores, matching the phi kernel.
    """
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)  # (S, [w,h])
    S = anchors.shape[0]

    def fn(xv, img):
        N, C, H, W = xv.shape
        attrs = C // S
        xv = xv.reshape(N, S, attrs, H, W)
        if iou_aware:
            iou_pred = jax.nn.sigmoid(xv[:, :, 0])           # [N,S,H,W]
            xv = xv[:, :, 1:]
        grid_x = jnp.arange(W, dtype=jnp.float32)
        grid_y = jnp.arange(H, dtype=jnp.float32)
        sx = float(scale_x_y)
        bias = -0.5 * (sx - 1.0)
        bx = (jax.nn.sigmoid(xv[:, :, 0]) * sx + bias + grid_x) / W
        by = (jax.nn.sigmoid(xv[:, :, 1]) * sx + bias
              + grid_y[:, None]) / H
        in_w = float(downsample_ratio) * W
        in_h = float(downsample_ratio) * H
        pw = anchors[:, 0][None, :, None, None] / in_w
        ph = anchors[:, 1][None, :, None, None] / in_h
        bw = jnp.exp(xv[:, :, 2]) * pw
        bh = jnp.exp(xv[:, :, 3]) * ph
        conf = jax.nn.sigmoid(xv[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) * \
                iou_pred ** iou_aware_factor
        cls = jax.nn.sigmoid(xv[:, :, 5:])                   # [N,S,cn,H,W]

        imgh = img[:, 0].astype(jnp.float32)[:, None, None, None]
        imgw = img[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imgw
        y1 = (by - bh / 2) * imgh
        x2 = (bx + bw / 2) * imgw
        y2 = (by + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, imgw - 1.0)
            y1 = jnp.clip(y1, 0.0, imgh - 1.0)
            x2 = jnp.clip(x2, 0.0, imgw - 1.0)
            y2 = jnp.clip(y2, 0.0, imgh - 1.0)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)          # [N,S,H,W,4]
        # phi kernel: anchors with conf < conf_thresh emit all-zero box
        # AND score rows (downstream consumers use zero boxes as the drop
        # marker); conf == thresh is kept
        keep = conf >= conf_thresh                            # [N,S,H,W]
        boxes = jnp.where(keep[..., None], boxes, 0.0)
        sc = conf[:, :, None] * cls                           # [N,S,cn,H,W]
        sc = jnp.where(keep[:, :, None], sc, 0.0)
        boxes = boxes.reshape(N, S * H * W, 4)
        sc = jnp.moveaxis(sc, 2, -1).reshape(N, S * H * W, class_num)
        return boxes, sc

    out = apply(fn, x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)),
                img_size if isinstance(img_size, Tensor)
                else Tensor(jnp.asarray(img_size)))
    return out


# ---------------------------------------------------------------------------
# Bilinear sampling helper (roi_align, deform_conv2d)
# ---------------------------------------------------------------------------

def _bilinear_gather(feat, ys, xs):
    """Sample feat [C, H, W] at fractional (ys, xs) [...]; zero outside
    [-1, H] x [-1, W] (phi kernels' boundary convention). Returns
    [C, ...]."""
    H, W = feat.shape[-2:]
    valid = (ys > -1.0) & (ys < H) & (xs > -1.0) & (xs < W)
    y = jnp.clip(ys, 0.0, H - 1.0)
    x = jnp.clip(xs, 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly = y - y0
    lx = x - x0
    hy = 1.0 - ly
    hx = 1.0 - lx
    v00 = feat[:, y0, x0]
    v01 = feat[:, y0, x1]
    v10 = feat[:, y1, x0]
    v11 = feat[:, y1, x1]
    out = (v00 * (hy * hx) + v01 * (hy * lx)
           + v10 * (ly * hx) + v11 * (ly * lx))
    return jnp.where(valid, out, 0.0)


def _batch_ids(boxes_num, num_rois):
    """Expand per-image box counts into a per-roi batch index (host-side:
    counts define static gather shapes, mirroring the phi rois_num path)."""
    counts = np.asarray(boxes_num, np.int64)
    return np.repeat(np.arange(len(counts)), counts).astype(np.int32)


# ---------------------------------------------------------------------------
# roi_align
# ---------------------------------------------------------------------------

def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (Mask R-CNN). boxes [R, 4] xyxy; boxes_num [N] per-image
    counts. Returns [R, C, ph, pw]. sampling_ratio <= 0 uses the adaptive
    ceil(bin) count, resolved on host from the (eager) box values —
    pass a positive sampling_ratio for fully-traced use."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bv = _val(boxes)
    bids = _batch_ids(np.asarray(_val(boxes_num)), bv.shape[0])

    def one_roi(feat, box, sh, sw):
        """Pool one roi from feat [C, H, W] with an sh x sw sample grid
        per bin (sh/sw static)."""
        off = 0.5 if aligned else 0.0
        bx = box * spatial_scale
        x1, y1 = bx[0] - off, bx[1] - off
        rw = bx[2] - bx[0]
        rh = bx[3] - bx[1]
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        ys = y1 + (jnp.arange(ph)[:, None]
                   + (jnp.arange(sh) + 0.5)[None, :] / sh) * bin_h  # [ph,sh]
        xs = x1 + (jnp.arange(pw)[:, None]
                   + (jnp.arange(sw) + 0.5)[None, :] / sw) * bin_w  # [pw,sw]
        yy = jnp.broadcast_to(ys[:, :, None, None], (ph, sh, pw, sw))
        xx = jnp.broadcast_to(xs[None, None, :, :], (ph, sh, pw, sw))
        vals = _bilinear_gather(feat, yy, xx)        # [C, ph, sh, pw, sw]
        return vals.mean(axis=(2, 4))                # [C, ph, pw]

    if sampling_ratio > 0:
        s = int(sampling_ratio)

        def fn(xv, bv):
            feats = xv[jnp.asarray(bids)]            # [R, C, H, W]
            return jax.vmap(lambda f, b: one_roi(f, b, s, s))(feats, bv)

        return apply(fn, x if isinstance(x, Tensor)
                     else Tensor(jnp.asarray(x)),
                     boxes if isinstance(boxes, Tensor)
                     else Tensor(jnp.asarray(boxes)))

    # adaptive (reference default): per-roi ceil(bin) sample counts are
    # data-dependent → resolved on host per roi (eager path; pass a
    # positive sampling_ratio for fully-traced use)
    b_host = np.asarray(jax.device_get(bv), np.float32)
    rw = (b_host[:, 2] - b_host[:, 0]) * spatial_scale
    rh = (b_host[:, 3] - b_host[:, 1]) * spatial_scale
    if not aligned:
        rw = np.maximum(rw, 1.0)
        rh = np.maximum(rh, 1.0)
    shs = np.maximum(np.ceil(rh / ph), 1).astype(int)
    sws = np.maximum(np.ceil(rw / pw), 1).astype(int)

    def fn(xv, bv):
        outs = []
        for r in range(bv.shape[0]):
            outs.append(one_roi(xv[int(bids[r])], bv[r],
                                int(shs[r]), int(sws[r])))
        return jnp.stack(outs, 0) if outs else \
            jnp.zeros((0, xv.shape[1], ph, pw), xv.dtype)

    return apply(fn, x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)),
                 boxes if isinstance(boxes, Tensor)
                 else Tensor(jnp.asarray(boxes)))


# ---------------------------------------------------------------------------
# roi_pool / psroi_pool — masked-sum einsum formulation
# ---------------------------------------------------------------------------

def _bin_masks(starts, ends, size):
    """Membership mask [..., size] of positions i with start <= i < end."""
    idx = jnp.arange(size, dtype=jnp.float32)
    return ((idx >= starts[..., None]) & (idx < ends[..., None])) \
        .astype(jnp.float32)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoI max pooling (Fast R-CNN). Quantized-bin max, phi rounding:
    start = round(coord * scale), bins floored/ceiled; empty bins -> 0."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bids = _batch_ids(np.asarray(_val(boxes_num)), _val(boxes).shape[0])

    def fn(xv, bv):
        N, C, H, W = xv.shape
        r0 = jnp.round(bv * spatial_scale)
        x1, y1, x2, y2 = r0[:, 0], r0[:, 1], r0[:, 2], r0[:, 3]
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        phi_ = jnp.arange(ph, dtype=jnp.float32)
        pwi = jnp.arange(pw, dtype=jnp.float32)
        hs = jnp.clip(jnp.floor(phi_[None] * bin_h[:, None]) + y1[:, None],
                      0, H)
        he = jnp.clip(jnp.ceil((phi_[None] + 1) * bin_h[:, None])
                      + y1[:, None], 0, H)
        ws = jnp.clip(jnp.floor(pwi[None] * bin_w[:, None]) + x1[:, None],
                      0, W)
        we = jnp.clip(jnp.ceil((pwi[None] + 1) * bin_w[:, None])
                      + x1[:, None], 0, W)
        mh = _bin_masks(hs, he, H)                            # [R, ph, H]
        mw = _bin_masks(ws, we, W)                            # [R, pw, W]
        feats = xv[jnp.asarray(bids)]                         # [R, C, H, W]
        neg = jnp.finfo(jnp.float32).min
        # one masked reduction per output bin, reusing the [R,C,H,W]
        # feature gather — a dense [R,C,ph,pw,H,W] broadcast would be
        # tens of GB at detection sizes
        rows = []
        for i in range(ph):
            cols = []
            for j in range(pw):
                m = mh[:, i, :, None] * mw[:, j, None, :]     # [R, H, W]
                v = jnp.where(m[:, None] > 0, feats, neg).max((-2, -1))
                cols.append(v)                                # [R, C]
            rows.append(jnp.stack(cols, -1))                  # [R, C, pw]
        out = jnp.stack(rows, -2)                             # [R,C,ph,pw]
        empty = (mh.sum(-1)[:, :, None] * mw.sum(-1)[:, None, :]) == 0
        return jnp.where(empty[:, None], 0.0, out)

    return apply(fn, x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)),
                 boxes if isinstance(boxes, Tensor)
                 else Tensor(jnp.asarray(boxes)))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (R-FCN). Input channels must
    equal out_channels * ph * pw; each output bin (c, ph, pw) averages its
    own input channel over the bin extent (phi rounding: round(coord),
    end+1, min-size 0.1)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    bids = _batch_ids(np.asarray(_val(boxes_num)), _val(boxes).shape[0])

    def fn(xv, bv):
        N, C, H, W = xv.shape
        if C % (ph * pw):
            raise ValueError(
                "psroi_pool: input channels must be a multiple of "
                f"output_size h*w, got {C} vs {ph}x{pw}")
        c_out = C // (ph * pw)
        rs = jnp.round(bv)
        y1 = rs[:, 1] * spatial_scale
        x1 = rs[:, 0] * spatial_scale
        y2 = (rs[:, 3] + 1.0) * spatial_scale
        x2 = (rs[:, 2] + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h = rh / ph
        bin_w = rw / pw
        phi_ = jnp.arange(ph, dtype=jnp.float32)
        pwi = jnp.arange(pw, dtype=jnp.float32)
        hs = jnp.clip(jnp.floor(phi_[None] * bin_h[:, None] + y1[:, None]),
                      0, H)
        he = jnp.clip(jnp.ceil((phi_[None] + 1) * bin_h[:, None]
                               + y1[:, None]), 0, H)
        ws = jnp.clip(jnp.floor(pwi[None] * bin_w[:, None] + x1[:, None]),
                      0, W)
        we = jnp.clip(jnp.ceil((pwi[None] + 1) * bin_w[:, None]
                               + x1[:, None]), 0, W)
        mh = _bin_masks(hs, he, H)                            # [R, ph, H]
        mw = _bin_masks(ws, we, W)                            # [R, pw, W]
        feats = xv[jnp.asarray(bids)]                         # [R, C, H, W]
        feats = feats.reshape(feats.shape[0], c_out, ph, pw, H, W)
        # masked sum contracted on the MXU: bin membership is a weight mask
        s = jnp.einsum("rcpqhw,rph,rqw->rcpq", feats, mh, mw)
        area = mh.sum(-1)[:, :, None] * mw.sum(-1)[:, None, :]  # [R,ph,pw]
        return jnp.where(area[:, None] > 0, s / jnp.maximum(area[:, None],
                                                            1.0), 0.0)

    return apply(fn, x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)),
                 boxes if isinstance(boxes, Tensor)
                 else Tensor(jnp.asarray(boxes)))


# ---------------------------------------------------------------------------
# deform_conv2d (DCNv1/v2)
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution. offset: [N, 2*dg*kh*kw, Hout, Wout] with
    channel pairs (dy, dx) per kernel tap (phi deformable_conv_functor
    layout); mask (DCNv2): [N, dg*kh*kw, Hout, Wout] multiplies the
    bilinear-sampled value. weight: [Cout, Cin/groups, kh, kw]."""
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def fn(xv, ov, wv, bv, mv):
        N, Cin, H, W = xv.shape
        Cout, _, kh, kw = wv.shape
        Ho, Wo = ov.shape[2], ov.shape[3]
        dg = deformable_groups
        K = kh * kw
        ov = ov.reshape(N, dg, K, 2, Ho, Wo)
        base_y = jnp.arange(Ho, dtype=jnp.float32) * stride[0] - padding[0]
        base_x = jnp.arange(Wo, dtype=jnp.float32) * stride[1] - padding[1]
        tap_y = (jnp.arange(K) // kw).astype(jnp.float32) * dilation[0]
        tap_x = (jnp.arange(K) % kw).astype(jnp.float32) * dilation[1]
        # unperturbed sample grid per kernel tap: [K, Ho, Wo]
        sample_y = tap_y[:, None, None] + base_y[None, :, None] \
            + jnp.zeros((1, 1, Wo))
        sample_x = tap_x[:, None, None] + base_x[None, None, :] \
            + jnp.zeros((1, Ho, 1))

        def per_image(feat, off_i, mask_i):
            # feat [Cin, H, W]; off_i [dg, K, 2, Ho, Wo]
            yy = sample_y[None] + off_i[:, :, 0]              # [dg,K,Ho,Wo]
            xx = sample_x[None] + off_i[:, :, 1]
            featg = feat.reshape(dg, Cin // dg, H, W)
            vals = jax.vmap(_bilinear_gather)(featg, yy, xx)  # [dg,cpg,K,..]
            if mask_i is not None:
                vals = vals * mask_i[:, None]
            return vals.reshape(Cin, K, Ho, Wo)

        if mv is not None:
            mvr = mv.reshape(N, dg, K, Ho, Wo)
            cols = jax.vmap(per_image)(xv, ov, mvr)
        else:
            cols = jax.vmap(lambda f, o: per_image(f, o, None))(xv, ov)
        # cols: [N, Cin, K, Ho, Wo]; contract with weight on the MXU
        cpg = Cin // groups
        opg = Cout // groups
        colsg = cols.reshape(N, groups, cpg, K, Ho, Wo)
        wg = wv.reshape(groups, opg, cpg, kh * kw)
        out = jnp.einsum("ngckhw,gock->ngohw", colsg, wg)
        out = out.reshape(N, Cout, Ho, Wo)
        if bv is not None:
            out = out + bv[None, :, None, None]
        return out

    args = [x, offset, weight]
    tensors = [a if isinstance(a, Tensor) else Tensor(jnp.asarray(a))
               for a in args]
    b_t = bias if bias is None or isinstance(bias, Tensor) \
        else Tensor(jnp.asarray(bias))
    m_t = mask if mask is None or isinstance(mask, Tensor) \
        else Tensor(jnp.asarray(mask))
    if b_t is not None and m_t is not None:
        return apply(fn, *tensors, b_t, m_t)
    if b_t is not None:
        return apply(lambda xv, ov, wv, bv: fn(xv, ov, wv, bv, None),
                     *tensors, b_t)
    if m_t is not None:
        return apply(lambda xv, ov, wv, mv: fn(xv, ov, wv, None, mv),
                     *tensors, m_t)
    return apply(lambda xv, ov, wv: fn(xv, ov, wv, None, None), *tensors)


class DeformConv2D(Layer):
    """Layer wrapper over deform_conv2d (reference vision/ops.py:1088)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, *ks],
            attr=weight_attr, default_initializer=I.XavierUniform())
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self._stride, self._padding, self._dilation,
                             self._deformable_groups, self._groups, mask)


# ---------------------------------------------------------------------------
# nms
# ---------------------------------------------------------------------------

def _box_iou_matrix(b):
    """Pairwise IoU of [R, 4] xyxy boxes (area convention of the phi nms
    kernel: plain (x2-x1)*(y2-y1))."""
    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _nms_keep_mask(boxes_sorted, iou_threshold):
    """Greedy suppression over pre-sorted boxes; returns bool keep mask.
    Device-side O(R²) sweep (one fori_loop over rows)."""
    iou = _box_iou_matrix(boxes_sorted)
    R = boxes_sorted.shape[0]

    def body(i, keep):
        sup = (iou[i] > iou_threshold) & (jnp.arange(R) > i) & keep[i]
        return keep & ~sup

    return lax.fori_loop(0, R, body, jnp.ones((R,), bool))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Reference vision/ops.py:2064. Greedy NMS; with scores, boxes are
    ranked by score first; with categories, NMS runs per category and
    results merge score-sorted; top_k truncates. Returns kept indices
    (int64, host-materialized — output size is data-dependent)."""
    bv = _val(boxes)
    if scores is None:
        keep = np.asarray(jax.device_get(_nms_keep_mask(bv, iou_threshold)))
        return Tensor(jnp.asarray(np.nonzero(keep)[0].astype(np.int64)))

    sv = _val(scores)
    if category_idxs is None:
        order = jnp.argsort(-sv)
        keep = _nms_keep_mask(bv[order], iou_threshold)
        keep_np = np.asarray(jax.device_get(keep))
        order_np = np.asarray(jax.device_get(order))
        out = order_np[np.nonzero(keep_np)[0]]
        if top_k is not None:
            out = out[:top_k]
        return Tensor(jnp.asarray(out.astype(np.int64)))

    assert categories is not None, \
        "categories is required when category_idxs is given"
    cv = np.asarray(jax.device_get(_val(category_idxs)))
    sv_np = np.asarray(jax.device_get(sv))
    kept = []
    for cat in categories:
        idxs = np.nonzero(cv == cat)[0]
        if idxs.size == 0:
            continue
        if idxs.size == 1:
            kept.append(idxs)
            continue
        order = idxs[np.argsort(-sv_np[idxs], kind="stable")]
        keep = np.asarray(jax.device_get(
            _nms_keep_mask(bv[jnp.asarray(order)], iou_threshold)))
        kept.append(order[keep])
    if kept:
        kept = np.concatenate(kept)
    else:
        kept = np.zeros((0,), np.int64)
    kept = kept[np.argsort(-sv_np[kept], kind="stable")]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept.astype(np.int64)))


# ---------------------------------------------------------------------------
# ConvNormActivation
# ---------------------------------------------------------------------------

_DEFAULT = object()


def ConvNormActivation(in_channels, out_channels, kernel_size=3, stride=1,
                       padding=None, groups=1, norm_layer=_DEFAULT,
                       activation_layer=_DEFAULT, dilation=1, bias=None):
    """Conv2D + norm + activation block (reference vision/ops.py:2007).
    norm_layer/activation_layer default to BatchNorm2D/ReLU; passing None
    explicitly SKIPS that stage (and a skipped norm enables the conv
    bias), matching the reference semantics."""
    from paddle_tpu.nn import BatchNorm2D, Conv2D, ReLU, Sequential

    if padding is None:
        padding = (kernel_size - 1) // 2 * dilation
    if norm_layer is _DEFAULT:
        norm_layer = BatchNorm2D
    if activation_layer is _DEFAULT:
        activation_layer = ReLU
    if bias is None:
        bias = norm_layer is None
    layers = [Conv2D(in_channels, out_channels, kernel_size, stride,
                     padding, dilation=dilation, groups=groups,
                     bias_attr=None if bias else False)]
    if norm_layer is not None:
        layers.append(norm_layer(out_channels))
    if activation_layer is not None:
        layers.append(activation_layer())
    return Sequential(*layers)


class PSRoIPool(Layer):
    """Reference vision/ops.py:1632."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


class RoIPool(Layer):
    """Reference vision/ops.py:1771."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class RoIAlign(Layer):
    """Reference vision/ops.py:1959."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


# --------------------------------------------------------------- box_coder
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Encode/decode target boxes against priors
    (reference vision/ops.py:649). Boxes are [xmin, ymin, xmax, ymax];
    encode: offsets of target centers/sizes w.r.t. priors scaled by the
    variances; decode inverts it. prior_box_var may be a Tensor
    ([M, 4]), a 4-list, or None."""
    pv = _val(prior_box)
    tv = _val(target_box)
    norm = 0.0 if box_normalized else 1.0

    pw = pv[:, 2] - pv[:, 0] + norm
    ph = pv[:, 3] - pv[:, 1] + norm
    px = pv[:, 0] + pw * 0.5
    py = pv[:, 1] + ph * 0.5

    if prior_box_var is None:
        var = jnp.ones((4,), pv.dtype)
    elif isinstance(prior_box_var, (list, tuple)):
        var = jnp.asarray(prior_box_var, pv.dtype)
    else:
        var = _val(prior_box_var)

    if code_type == "encode_center_size":
        # target [N, 4] against every prior -> [N, M, 4]
        tw = tv[:, 2] - tv[:, 0] + norm
        th = tv[:, 3] - tv[:, 1] + norm
        tx = tv[:, 0] + tw * 0.5
        ty = tv[:, 1] + th * 0.5
        ox = (tx[:, None] - px[None, :]) / pw[None, :]
        oy = (ty[:, None] - py[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        out = out / (var[None, None, :] if var.ndim == 1
                     else var[None, :, :])
        return Tensor(out)

    if code_type == "decode_center_size":
        # target [N, M, 4] offsets; priors broadcast along `axis`
        exp = (lambda a: a[None, :, :]) if axis == 0 else \
            (lambda a: a[:, None, :])
        pwe = pw[None, :] if axis == 0 else pw[:, None]
        phe = ph[None, :] if axis == 0 else ph[:, None]
        pxe = px[None, :] if axis == 0 else px[:, None]
        pye = py[None, :] if axis == 0 else py[:, None]
        v = var[None, None, :] if var.ndim == 1 else exp(var)
        t = tv * v
        ox = pwe * t[:, :, 0] + pxe
        oy = phe * t[:, :, 1] + pye
        ow = jnp.exp(t[:, :, 2]) * pwe
        oh = jnp.exp(t[:, :, 3]) * phe
        out = jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                         ox + ow * 0.5 - norm, oy + oh * 0.5 - norm], -1)
        return Tensor(out)
    raise ValueError("code_type must be encode_center_size or "
                     "decode_center_size")


# --------------------------------------------------------------- prior_box
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (reference vision/ops.py:477): each feature-map
    cell emits boxes for every (min_size, aspect_ratio) pair (+ the
    sqrt(min*max) box).  Returns (boxes [H, W, P, 4], variances same
    shape).  Pure static shape math — computed host-side in numpy, the
    same way the reference's CPU kernel does."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    min_sizes = [float(m) for m in (min_sizes if isinstance(
        min_sizes, (list, tuple)) else [min_sizes])]
    max_sizes = [float(m) for m in (max_sizes or [])]
    if max_sizes:
        assert len(max_sizes) == len(min_sizes)
    ars = [1.0]
    for ar in (aspect_ratios if isinstance(aspect_ratios, (list, tuple))
               else [aspect_ratios]):
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh

    boxes_per_cell = []
    for k, ms in enumerate(min_sizes):
        cell = []
        # aspect-ratio boxes of min_size (ar==1 first)
        for ar in ars:
            cell.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            big = np.sqrt(ms * max_sizes[k])
            if min_max_aspect_ratios_order:
                cell.insert(1, (big, big))
            else:
                cell.append((big, big))
        boxes_per_cell.extend(cell)

    p = len(boxes_per_cell)
    wh = np.asarray(boxes_per_cell, np.float32)  # [P, 2]
    cx = (np.arange(fw, dtype=np.float32) + offset) * step_w
    cy = (np.arange(fh, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)  # [H, W]
    out = np.zeros((fh, fw, p, 4), np.float32)
    out[..., 0] = (cxg[:, :, None] - wh[None, None, :, 0] / 2) / iw
    out[..., 1] = (cyg[:, :, None] - wh[None, None, :, 1] / 2) / ih
    out[..., 2] = (cxg[:, :, None] + wh[None, None, :, 0] / 2) / iw
    out[..., 3] = (cyg[:, :, None] + wh[None, None, :, 1] / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


# -------------------------------------------------------------- matrix_nms
def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference vision/ops.py:2425): instead of greedy
    suppression, every selected box's score decays by the max IoU with
    any higher-scored box of its class (gaussian or linear decay) — one
    IoU MATRIX per class, no sequential loop: the formulation SOLOv2
    introduced because it vectorizes (ideal for the MXU).  Returns
    ([No, 6] detections, index, rois_num) with host-materialized counts
    like ops.nms."""
    bv = np.asarray(jax.device_get(_val(bboxes)), np.float32)   # [N, M, 4]
    sv = np.asarray(jax.device_get(_val(scores)), np.float32)   # [N, C, M]
    n, c, m = sv.shape
    norm_off = 0.0 if normalized else 1.0

    def np_iou(b):
        # numpy IoU matrix (no device round-trip: this whole routine is
        # host-side post-processing); +1 widths for pixel boxes like the
        # reference's normalized=False convention
        w = np.maximum(b[:, 2] - b[:, 0] + norm_off, 0)
        h = np.maximum(b[:, 3] - b[:, 1] + norm_off, 0)
        area = w * h
        ix = np.maximum(
            np.minimum(b[:, None, 2], b[None, :, 2])
            - np.maximum(b[:, None, 0], b[None, :, 0]) + norm_off, 0)
        iy = np.maximum(
            np.minimum(b[:, None, 3], b[None, :, 3])
            - np.maximum(b[:, None, 1], b[None, :, 1]) + norm_off, 0)
        inter = ix * iy
        return inter / np.maximum(area[:, None] + area[None, :] - inter,
                                  1e-10)

    outs, idxs, nums = [], [], []
    for b in range(n):
        dets, sel = [], []
        for cls in range(c):
            if cls == background_label:
                continue
            s = sv[b, cls]
            keep = np.nonzero(s > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-s[keep], kind="stable")]
            if nms_top_k > -1:
                order = order[:nms_top_k]
            boxes = bv[b, order]
            ss = s[order]
            iou = np_iou(boxes)
            k = len(order)
            tri = np.triu(iou, 1)                    # IoU with higher-ranked
            max_iou = tri.max(axis=0) if k > 1 else np.zeros(k)
            # decay_j = min_i f(iou_ij) / f(max_iou_i) over higher-ranked i
            if use_gaussian:
                f = lambda x: np.exp(-(x ** 2) / gaussian_sigma)
            else:
                f = lambda x: 1.0 - x
            comp = max_iou[:, None] if k > 1 else np.zeros((k, 1))
            decay = (f(tri) / f(comp))
            decay = np.where(np.triu(np.ones((k, k), bool), 1), decay, 1.0)
            decay = decay.min(axis=0)
            new_scores = ss * decay
            survived = np.nonzero(new_scores > post_threshold)[0]
            for j in survived:
                dets.append([float(cls), float(new_scores[j]), *boxes[j]])
                sel.append(b * m + int(order[j]))
        if dets:
            order = np.argsort(-np.asarray(dets)[:, 1], kind="stable")
            if keep_top_k > -1:
                order = order[:keep_top_k]
            outs.append(np.asarray(dets, np.float32)[order])
            idxs.append(np.asarray(sel, np.int64)[order])
            nums.append(len(order))
        else:
            nums.append(0)
    out = np.concatenate(outs, 0) if outs else np.zeros((0, 6), np.float32)
    index = (np.concatenate(idxs, 0) if idxs
             else np.zeros((0,), np.int64))[:, None]
    rois_num = np.asarray(nums, np.int32)
    rets = [Tensor(jnp.asarray(out))]
    if return_index:
        rets.append(Tensor(jnp.asarray(index)))
    if return_rois_num:
        rets.append(Tensor(jnp.asarray(rois_num)))
    return tuple(rets) if len(rets) > 1 else rets[0]


# ------------------------------------------------- distribute_fpn_proposals
def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference vision/ops.py:1288):
    level = floor(refer_level + log2(sqrt(area) / refer_scale)), clipped
    to [min_level, max_level].  Returns (multi_rois list, restore_ind,
    rois_num_per_level list); with `rois_num` ([N] per-image counts) each
    level's count tensor is per-image, so downstream per-level
    roi_align(boxes_num=...) can still split by image."""
    rv = np.asarray(jax.device_get(_val(fpn_rois)), np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = rv[:, 2] - rv[:, 0] + off
    h = rv[:, 3] - rv[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(refer_level + np.log2(scale / refer_scale + 1e-12))
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)

    if rois_num is not None:
        counts = np.asarray(jax.device_get(_val(rois_num)),
                            np.int64).reshape(-1)
        img_of = np.repeat(np.arange(counts.size), counts)
    else:
        counts = None
        img_of = np.zeros(rv.shape[0], np.int64)

    multi_rois, restore, nums = [], [], []
    for level in range(min_level, max_level + 1):
        pos = np.nonzero(lvl == level)[0]
        multi_rois.append(Tensor(jnp.asarray(rv[pos])))
        if counts is not None:
            per_img = np.asarray(
                [(img_of[pos] == i).sum() for i in range(counts.size)],
                np.int32)
        else:
            per_img = np.asarray([pos.size], np.int32)
        nums.append(Tensor(jnp.asarray(per_img)))
        restore.append(pos)
    order = np.concatenate(restore) if restore else np.zeros(0, np.int64)
    restore_ind = np.empty_like(order)
    restore_ind[order] = np.arange(order.size)
    return multi_rois, Tensor(jnp.asarray(restore_ind[:, None]
                                          .astype(np.int32))), nums


# --------------------------------------------------------------- yolo_loss
def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference vision/ops.py:52): location SSE + obj/noobj
    + class BCE per grid anchor.  Fully vectorized jnp (the reference is
    a CUDA kernel); best-anchor assignment by IoU of wh shapes, ignore
    mask from predicted-box IoU against every gt."""
    xv = _val(x)
    gb = _val(gt_box).astype(jnp.float32)     # [N, B, 4] cx cy w h (norm)
    gl = _val(gt_label).astype(jnp.int32)     # [N, B]
    nb, ch, hh, ww = xv.shape
    s = len(anchor_mask)
    assert ch == s * (5 + class_num), "channel/anchor mismatch"
    pred = xv.reshape(nb, s, 5 + class_num, hh, ww)

    an = np.asarray(anchors, np.float32).reshape(-1, 2)
    masked = an[np.asarray(anchor_mask)]
    in_w = ww * downsample_ratio
    in_h = hh * downsample_ratio

    tx = jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y \
        - (scale_x_y - 1) / 2                       # [N, S, H, W]
    ty = jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y \
        - (scale_x_y - 1) / 2
    tw = pred[:, :, 2]
    th = pred[:, :, 3]
    tobj = pred[:, :, 4]
    tcls = pred[:, :, 5:]                            # [N, S, C, H, W]

    gx = jnp.arange(ww, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(hh, dtype=jnp.float32)[None, None, :, None]
    aw = jnp.asarray(masked[:, 0])[None, :, None, None]
    ahh = jnp.asarray(masked[:, 1])[None, :, None, None]
    px = (tx + gx) / ww
    py = (ty + gy) / hh
    pw = jnp.exp(jnp.clip(tw, -10, 10)) * aw / in_w
    ph = jnp.exp(jnp.clip(th, -10, 10)) * ahh / in_h

    # ignore mask: max IoU of each predicted box vs every gt of the image
    def box_iou_cw(px, py, pw, ph, g):
        # g: [B, 4]
        x1 = px - pw / 2
        y1 = py - ph / 2
        x2 = px + pw / 2
        y2 = py + ph / 2
        gx1 = (g[:, 0] - g[:, 2] / 2)[:, None, None, None]
        gy1 = (g[:, 1] - g[:, 3] / 2)[:, None, None, None]
        gx2 = (g[:, 0] + g[:, 2] / 2)[:, None, None, None]
        gy2 = (g[:, 1] + g[:, 3] / 2)[:, None, None, None]
        iw = jnp.maximum(jnp.minimum(x2[None], gx2)
                         - jnp.maximum(x1[None], gx1), 0)
        ih = jnp.maximum(jnp.minimum(y2[None], gy2)
                         - jnp.maximum(y1[None], gy1), 0)
        inter = iw * ih
        union = pw * ph + (g[:, 2] * g[:, 3])[:, None, None, None] - inter
        return inter / jnp.maximum(union, 1e-10)

    iou_all = jax.vmap(box_iou_cw)(px, py, pw, ph, gb)  # [N, B, S, H, W]
    valid_gt = (gb[:, :, 2] > 0)[:, :, None, None, None]
    best_iou = jnp.where(valid_gt, iou_all, 0.0).max(axis=1)
    ignore = best_iou > ignore_thresh

    # gt assignment: best anchor (over ALL anchors) by wh IoU; only
    # anchors in anchor_mask contribute to this scale's loss
    gw = gb[:, :, 2] * in_w
    gh = gb[:, :, 3] * in_h
    inter = jnp.minimum(gw[:, :, None], an[None, None, :, 0]) * \
        jnp.minimum(gh[:, :, None], an[None, None, :, 1])
    union = gw[:, :, None] * gh[:, :, None] \
        + (an[:, 0] * an[:, 1])[None, None, :] - inter
    best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-10), -1)

    gi = jnp.clip((gb[:, :, 0] * ww).astype(jnp.int32), 0, ww - 1)
    gj = jnp.clip((gb[:, :, 1] * hh).astype(jnp.int32), 0, hh - 1)

    loss = jnp.zeros((nb,), jnp.float32)
    mask_arr = np.asarray(anchor_mask)
    smooth = 1.0 / max(class_num, 1) if use_label_smooth else 0.0
    score = _val(gt_score).astype(jnp.float32) if gt_score is not None \
        else jnp.ones(gl.shape, jnp.float32)

    obj_target = jnp.zeros((nb, s, hh, ww), jnp.float32)
    obj_weight = jnp.zeros((nb, s, hh, ww), jnp.float32)
    for si, a_idx in enumerate(mask_arr):
        sel = (best_anchor == a_idx) & (gb[:, :, 2] > 0)   # [N, B]
        w_box = (2.0 - gb[:, :, 2] * gb[:, :, 3]) * sel * score
        tgt_x = gb[:, :, 0] * ww - gi.astype(jnp.float32)
        tgt_y = gb[:, :, 1] * hh - gj.astype(jnp.float32)
        tgt_w = jnp.where(sel, jnp.log(jnp.maximum(
            gw / an[a_idx, 0], 1e-9)), 0.0)
        tgt_h = jnp.where(sel, jnp.log(jnp.maximum(
            gh / an[a_idx, 1], 1e-9)), 0.0)
        bidx = jnp.arange(nb)[:, None]
        px_sel = tx[bidx, si, gj, gi]
        py_sel = ty[bidx, si, gj, gi]
        pw_sel = tw[bidx, si, gj, gi]
        ph_sel = th[bidx, si, gj, gi]
        loss = loss + (w_box * ((px_sel - tgt_x) ** 2
                                + (py_sel - tgt_y) ** 2
                                + (pw_sel - tgt_w) ** 2
                                + (ph_sel - tgt_h) ** 2)).sum(-1)
        cls_sel = tcls[bidx, si, :, gj, gi]     # [N, B, C]
        onehot = jax.nn.one_hot(gl, class_num) * (1 - smooth) + \
            smooth / max(class_num, 1)
        bce = jnp.maximum(cls_sel, 0) - cls_sel * onehot + \
            jnp.log1p(jnp.exp(-jnp.abs(cls_sel)))
        loss = loss + (bce.sum(-1) * sel * score).sum(-1)
        obj_target = obj_target.at[bidx, si, gj, gi].max(
            sel.astype(jnp.float32))
        obj_weight = obj_weight.at[bidx, si, gj, gi].max(
            (sel * score).astype(jnp.float32))

    noobj = (1.0 - obj_target) * (1.0 - ignore.astype(jnp.float32))
    obj_bce = jnp.maximum(tobj, 0) - tobj * obj_target + \
        jnp.log1p(jnp.exp(-jnp.abs(tobj)))
    loss = loss + (obj_bce * (obj_target * jnp.maximum(obj_weight, 0.0)
                              + noobj)).sum((1, 2, 3))
    return Tensor(loss)


# --------------------------------------------------------- generate_proposals
def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference vision/ops.py:2236): decode
    anchor deltas, clip to the image, drop tiny boxes, greedy-NMS and
    keep post_nms_top_n per image.  Returns (rpn_rois, rpn_roi_probs[,
    rpn_rois_num]) like the reference."""
    sv = np.asarray(jax.device_get(_val(scores)), np.float32)  # [N,A,H,W]
    dv = np.asarray(jax.device_get(_val(bbox_deltas)), np.float32)
    iv = np.asarray(jax.device_get(_val(img_size)), np.float32)
    av = np.asarray(jax.device_get(_val(anchors)),
                    np.float32).reshape(-1, 4)
    vv = np.asarray(jax.device_get(_val(variances)),
                    np.float32).reshape(-1, 4)
    n, a, h, w = sv.shape
    off = 1.0 if pixel_offset else 0.0

    rois_all, probs_all, num_all = [], [], []
    for b in range(n):
        s = sv[b].transpose(1, 2, 0).reshape(-1)
        d = dv[b].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s, kind="stable")[:pre_nms_top_n]
        s = s[order]
        d = d[order]
        anc = av[order % av.shape[0]] if av.shape[0] != s.size \
            else av[order]
        var = vv[order % vv.shape[0]] if vv.shape[0] != s.size \
            else vv[order]
        aw = anc[:, 2] - anc[:, 0] + off
        ah = anc[:, 3] - anc[:, 1] + off
        ax = anc[:, 0] + aw * 0.5
        ay = anc[:, 1] + ah * 0.5
        dx, dy, dw, dh = (d * var).T
        cx = dx * aw + ax
        cy = dy * ah + ay
        bw = np.exp(np.clip(dw, -10, 10)) * aw
        bh = np.exp(np.clip(dh, -10, 10)) * ah
        boxes = np.stack([cx - bw / 2, cy - bh / 2,
                          cx + bw / 2 - off, cy + bh / 2 - off], -1)
        ih, iw = iv[b, 0], iv[b, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - off)
        ok = ((boxes[:, 2] - boxes[:, 0] + off >= min_size)
              & (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, s = boxes[ok], s[ok]
        if eta < 1.0:
            # adaptive NMS (reference :2236): loosen the threshold each
            # round while it stays meaningful, re-running on survivors
            thresh = nms_thresh
            cur = np.arange(boxes.shape[0])
            while True:
                keep = np.asarray(jax.device_get(_nms_keep_mask(
                    jnp.asarray(boxes[cur]), thresh)))
                cur = cur[np.nonzero(keep)[0]]
                thresh *= eta
                if thresh < 0.5 or cur.size <= post_nms_top_n:
                    break
            kept = cur[:post_nms_top_n]
        else:
            keep = np.asarray(jax.device_get(_nms_keep_mask(
                jnp.asarray(boxes), nms_thresh)))
            kept = np.nonzero(keep)[0][:post_nms_top_n]
        rois_all.append(boxes[kept])
        probs_all.append(s[kept])
        num_all.append(len(kept))
    rois = np.concatenate(rois_all, 0) if rois_all else \
        np.zeros((0, 4), np.float32)
    probs = (np.concatenate(probs_all, 0) if probs_all
             else np.zeros((0,), np.float32))[:, None]
    rpn_rois = Tensor(jnp.asarray(rois))
    rpn_roi_probs = Tensor(jnp.asarray(probs))
    nums = Tensor(jnp.asarray(np.asarray(num_all, np.int32)))
    if return_rois_num:
        return rpn_rois, rpn_roi_probs, nums
    return rpn_rois, rpn_roi_probs
