"""Folder datasets: train on a local image directory.

Reference: python/paddle/vision/datasets/folder.py (DatasetFolder :66,
ImageFolder :314) — the "root/class_x/img.ext" directory convention.

TPU-native notes: items come back as numpy HWC uint8 arrays (the layout
the transforms pipeline and the C++ prefetch ring consume); decoding is
host-side work that belongs on the data pipeline, never on the chip.
Decoding uses PIL when present (it is in this image) and falls back to a
clear error otherwise — zero-egress either way.
"""
from __future__ import annotations

import os

import numpy as np

from paddle_tpu.io import Dataset

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")

__all__ = ["DatasetFolder", "ImageFolder", "default_loader",
           "IMG_EXTENSIONS"]


def default_loader(path):
    """Load one image file as an HWC uint8 RGB numpy array."""
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "image loading needs PIL (pillow); pass a custom `loader` to "
            "DatasetFolder/ImageFolder to decode without it") from e
    with Image.open(path) as img:
        return np.asarray(img.convert("RGB"))


def has_valid_extension(filename, extensions):
    return filename.lower().endswith(tuple(extensions))


def make_dataset(directory, class_to_idx, extensions=None,
                 is_valid_file=None):
    """Walk root/class_x/**, returning [(path, class_idx), ...] sorted."""
    if (extensions is None) == (is_valid_file is None):
        raise ValueError(
            "exactly one of `extensions` and `is_valid_file` must be set")
    if extensions is not None:
        def is_valid_file(p):  # noqa: F811
            return has_valid_extension(p, extensions)
    samples = []
    directory = os.path.expanduser(directory)
    for cls in sorted(class_to_idx):
        d = os.path.join(directory, cls)
        if not os.path.isdir(d):
            continue
        for root, _, fnames in sorted(os.walk(d, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root, fname)
                if is_valid_file(path):
                    samples.append((path, class_to_idx[cls]))
    return samples


class DatasetFolder(Dataset):
    """root/class_a/x.ext layout -> (image, class_index) samples.

    Attributes match the reference: `classes`, `class_to_idx`, `samples`,
    `targets`.
    """

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        root = os.path.expanduser(root)
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        self.extensions = extensions
        classes = [d.name for d in os.scandir(root) if d.is_dir()]
        classes.sort()
        if not classes:
            raise RuntimeError(f"no class directories found under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = make_dataset(root, self.class_to_idx, extensions,
                                    is_valid_file)
        if not self.samples:
            raise RuntimeError(
                f"no valid files found under {root} (extensions="
                f"{extensions})")
        self.targets = [t for _, t in self.samples]

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, np.asarray(target, np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat (or nested) image directory -> [image] samples, no labels.

    Reference: vision/datasets/folder.py:314 — items are single-element
    lists, matching the reference's return convention.
    """

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        root = os.path.expanduser(root)
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is not None and is_valid_file is not None:
            raise ValueError(
                "exactly one of `extensions` and `is_valid_file` must be "
                "set")  # same contract as DatasetFolder/make_dataset
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if is_valid_file is None:
            def is_valid_file(p):
                return has_valid_extension(p, extensions)
        samples = []
        for root_, _, fnames in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root_, fname)
                if is_valid_file(path):
                    samples.append(path)
        if not samples:
            raise RuntimeError(f"no valid files found under {root}")
        self.samples = samples

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
