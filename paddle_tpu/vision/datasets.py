"""Vision datasets. Reference: python/paddle/vision/datasets/*.

Zero-egress environment: datasets synthesize deterministic procedural data
when the on-disk files are absent (download=False semantics), keeping the
full Dataset API so training pipelines run unmodified.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from paddle_tpu.io import Dataset


class MNIST(Dataset):
    """Reference: python/paddle/vision/datasets/mnist.py."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        loaded = False
        if image_path and label_path and os.path.exists(image_path) \
                and os.path.exists(label_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
            loaded = True
        if not loaded:
            # deterministic synthetic digits: class-dependent patterns.
            # The class-defining base patterns are SHARED between splits
            # (fixed seed) — only labels and per-sample noise differ — so
            # a model trained on `train` generalizes to `test` the way a
            # real dataset's splits do.
            n = 6000 if mode == "train" else 1000
            base = np.random.default_rng(1234).normal(
                0, 1, (10, 28, 28)).astype(np.float32)
            rng = np.random.default_rng(42 if mode == "train" else 7)
            self.labels = rng.integers(0, 10, n).astype(np.int64)
            noise = rng.normal(0, 0.3, (n, 28, 28)).astype(np.float32)
            img = base[self.labels] + noise
            # FIXED normalization bounds (±4 sigma of base+noise), not
            # per-split min/max: identical patterns must map to identical
            # pixel values in every split
            img = np.clip((img + 4.0) / 8.0, 0.0, 1.0)
            self.images = (img * 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None] / 255.0
        return img, np.asarray(label, np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """Reference: python/paddle/vision/datasets/cifar.py."""

    _classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        n = 5000 if mode == "train" else 1000
        # class patterns shared across splits (see MNIST note)
        base = np.random.default_rng(4321).normal(
            0, 1, (self._classes, 32, 32, 3)).astype(np.float32)
        rng = np.random.default_rng(1 if mode == "train" else 2)
        self.labels = rng.integers(0, self._classes, n).astype(np.int64)
        noise = rng.normal(0, 0.4, (n, 32, 32, 3)).astype(np.float32)
        img = base[self.labels] + noise
        img = np.clip((img + 4.0) / 8.0, 0.0, 1.0)  # fixed bounds
        self.data = (img * 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = np.transpose(img.astype(np.float32) / 255.0, (2, 0, 1))
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    _classes = 100


class Flowers(Cifar10):
    _classes = 102


class VOC2012(Dataset):
    """Semantic-segmentation pairs (image, mask) with the VOC 21-class
    space (reference vision/datasets/voc2012.py). Zero-egress: splits
    share fixed per-class blob layouts (seeded) so train generalizes to
    val the way the real splits do; masks are int64 [H, W] in [0, 20]
    with 255 as the ignore border, images float32 [3, H, W]."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.backend = backend or "pil"
        n = 128 if mode in ("train", "trainval") else 32
        hw = 64
        base = np.random.default_rng(2012)
        # per-class blob prototypes shared across splits
        protos = []
        for c in range(21):
            cy, cx = base.integers(8, hw - 8, 2)
            r = int(base.integers(6, 16))
            color = base.random(3).astype(np.float32)
            protos.append((cy, cx, r, color))
        rng = np.random.default_rng(1 if mode in ("train", "trainval")
                                    else 2)
        self.images, self.labels = [], []
        yy, xx = np.mgrid[0:hw, 0:hw]
        for _ in range(n):
            img = rng.random((3, hw, hw)).astype(np.float32) * 0.2
            mask = np.zeros((hw, hw), np.int64)
            for c in rng.choice(20, size=rng.integers(1, 4),
                                replace=False) + 1:
                cy, cx, r, color = protos[c]
                dy = int(rng.integers(-6, 7))
                dx = int(rng.integers(-6, 7))
                blob = ((yy - cy - dy) ** 2 + (xx - cx - dx) ** 2) <= r * r
                mask[blob] = c
                img[:, blob] = color[:, None] + rng.normal(
                    0, 0.05, (3, int(blob.sum()))).astype(np.float32)
            # VOC marks object borders with the ignore index
            border = np.zeros_like(mask, bool)
            border[:1, :] = border[-1:, :] = True
            border[:, :1] = border[:, -1:] = True
            mask[border] = 255
            self.images.append(np.clip(img, 0.0, 1.0))
            self.labels.append(mask)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


# folder datasets (train on a local image directory) — r4, VERDICT #7
from paddle_tpu.vision.folder import (  # noqa: E402,F401
    DatasetFolder,
    ImageFolder,
    default_loader,
)
