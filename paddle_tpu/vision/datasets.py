"""Vision datasets. Reference: python/paddle/vision/datasets/*.

Zero-egress environment: datasets synthesize deterministic procedural data
when the on-disk files are absent (download=False semantics), keeping the
full Dataset API so training pipelines run unmodified.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from paddle_tpu.io import Dataset


class MNIST(Dataset):
    """Reference: python/paddle/vision/datasets/mnist.py."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        loaded = False
        if image_path and label_path and os.path.exists(image_path) \
                and os.path.exists(label_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
            loaded = True
        if not loaded:
            # deterministic synthetic digits: class-dependent patterns.
            # The class-defining base patterns are SHARED between splits
            # (fixed seed) — only labels and per-sample noise differ — so
            # a model trained on `train` generalizes to `test` the way a
            # real dataset's splits do.
            n = 6000 if mode == "train" else 1000
            base = np.random.default_rng(1234).normal(
                0, 1, (10, 28, 28)).astype(np.float32)
            rng = np.random.default_rng(42 if mode == "train" else 7)
            self.labels = rng.integers(0, 10, n).astype(np.int64)
            noise = rng.normal(0, 0.3, (n, 28, 28)).astype(np.float32)
            img = base[self.labels] + noise
            # FIXED normalization bounds (±4 sigma of base+noise), not
            # per-split min/max: identical patterns must map to identical
            # pixel values in every split
            img = np.clip((img + 4.0) / 8.0, 0.0, 1.0)
            self.images = (img * 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None] / 255.0
        return img, np.asarray(label, np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """Reference: python/paddle/vision/datasets/cifar.py."""

    _classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        n = 5000 if mode == "train" else 1000
        # class patterns shared across splits (see MNIST note)
        base = np.random.default_rng(4321).normal(
            0, 1, (self._classes, 32, 32, 3)).astype(np.float32)
        rng = np.random.default_rng(1 if mode == "train" else 2)
        self.labels = rng.integers(0, self._classes, n).astype(np.int64)
        noise = rng.normal(0, 0.4, (n, 32, 32, 3)).astype(np.float32)
        img = base[self.labels] + noise
        img = np.clip((img + 4.0) / 8.0, 0.0, 1.0)  # fixed bounds
        self.data = (img * 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = np.transpose(img.astype(np.float32) / 255.0, (2, 0, 1))
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    _classes = 100


class Flowers(Cifar10):
    _classes = 102


# folder datasets (train on a local image directory) — r4, VERDICT #7
from paddle_tpu.vision.folder import (  # noqa: E402,F401
    DatasetFolder,
    ImageFolder,
    default_loader,
)
