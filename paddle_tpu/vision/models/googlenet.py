"""GoogLeNet (Inception v1). Reference:
python/paddle/vision/models/googlenet.py — returns (out, out1, out2) with the
two auxiliary classifier heads, like the reference."""
from __future__ import annotations

import paddle_tpu
import paddle_tpu.nn as nn


class ConvLayer(nn.Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 groups=1):
        super().__init__()
        self.conv = nn.Conv2D(num_channels, num_filters, filter_size,
                              stride=stride,
                              padding=(filter_size - 1) // 2, groups=groups,
                              bias_attr=False)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.conv(x))


class Inception(nn.Layer):
    def __init__(self, input_channels, output_channels, filter1, filter3R,
                 filter3, filter5R, filter5, proj):
        super().__init__()
        self.branch1 = ConvLayer(input_channels, filter1, 1)
        self.branch2_a = ConvLayer(input_channels, filter3R, 1)
        self.branch2_b = ConvLayer(filter3R, filter3, 3)
        self.branch3_a = ConvLayer(input_channels, filter5R, 1)
        self.branch3_b = ConvLayer(filter5R, filter5, 5)
        self.branch4_pool = nn.MaxPool2D(3, stride=1, padding=1)
        self.branch4_conv = ConvLayer(input_channels, proj, 1)

    def forward(self, x):
        return paddle_tpu.concat([
            self.branch1(x),
            self.branch2_b(self.branch2_a(x)),
            self.branch3_b(self.branch3_a(x)),
            self.branch4_conv(self.branch4_pool(x)),
        ], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = ConvLayer(3, 64, 7, stride=2)
        self.pool1 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.conv2_1 = ConvLayer(64, 64, 1)
        self.conv2_2 = ConvLayer(64, 192, 3)
        self.pool2 = nn.MaxPool2D(3, stride=2, ceil_mode=True)

        self.ince3a = Inception(192, 192, 64, 96, 128, 16, 32, 32)
        self.ince3b = Inception(256, 256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, ceil_mode=True)

        self.ince4a = Inception(480, 480, 192, 96, 208, 16, 48, 64)
        self.ince4b = Inception(512, 512, 160, 112, 224, 24, 64, 64)
        self.ince4c = Inception(512, 512, 128, 128, 256, 24, 64, 64)
        self.ince4d = Inception(512, 512, 112, 144, 288, 32, 64, 64)
        self.ince4e = Inception(528, 528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, ceil_mode=True)

        self.ince5a = Inception(832, 832, 256, 160, 320, 32, 128, 128)
        self.ince5b = Inception(832, 832, 384, 192, 384, 48, 128, 128)

        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.4)
        if num_classes > 0:
            self.out = nn.Linear(1024, num_classes)
            # aux heads: reference fc dims are 128*3*3=1152; adaptive pool
            # pins the 3x3 spatial for any input size (the reference's fixed
            # AvgPool2D(5,3) only matches at its blessed input resolution)
            self.pool_o1 = nn.AdaptiveAvgPool2D((3, 3))
            self.conv_o1 = ConvLayer(512, 128, 1)
            self.fc_o1 = nn.Linear(1152, 1024)
            self.dropout_o1 = nn.Dropout(0.7)
            self.out_o1 = nn.Linear(1024, num_classes)
            # aux head 2
            self.pool_o2 = nn.AdaptiveAvgPool2D((3, 3))
            self.conv_o2 = ConvLayer(528, 128, 1)
            self.fc_o2 = nn.Linear(1152, 1024)
            self.dropout_o2 = nn.Dropout(0.7)
            self.out_o2 = nn.Linear(1024, num_classes)
        self.relu = nn.ReLU()

    def forward(self, x):
        from paddle_tpu.tensor.manipulation import flatten
        x = self.pool1(self.conv1(x))
        x = self.pool2(self.conv2_2(self.conv2_1(x)))
        x = self.pool3(self.ince3b(self.ince3a(x)))
        ince4a = self.ince4a(x)
        ince4d = self.ince4d(self.ince4c(self.ince4b(ince4a)))
        x = self.pool4(self.ince4e(ince4d))
        x = self.ince5b(self.ince5a(x))
        if self.with_pool:
            x = self.pool5(x)
        x = self.dropout(x)
        if self.num_classes <= 0:
            return x
        out = self.out(flatten(x, 1))

        o1 = self.conv_o1(self.pool_o1(ince4a))
        o1 = self.relu(self.fc_o1(flatten(o1, 1)))
        out1 = self.out_o1(self.dropout_o1(o1))

        o2 = self.conv_o2(self.pool_o2(ince4d))
        o2 = self.relu(self.fc_o2(flatten(o2, 1)))
        out2 = self.out_o2(self.dropout_o2(o2))
        return [out, out1, out2]


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)
