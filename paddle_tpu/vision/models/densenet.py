"""DenseNet. Reference: python/paddle/vision/models/densenet.py."""
from __future__ import annotations

import paddle_tpu
import paddle_tpu.nn as nn


class BNACConvLayer(nn.Layer):
    """BN -> ReLU -> Conv (pre-activation)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 pad=0, groups=1):
        super().__init__()
        self.batch_norm = nn.BatchNorm2D(num_channels)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(num_channels, num_filters, filter_size,
                              stride=stride, padding=pad, groups=groups,
                              bias_attr=False)

    def forward(self, x):
        return self.conv(self.relu(self.batch_norm(x)))


class DenseLayer(nn.Layer):
    def __init__(self, num_channels, growth_rate, bn_size, dropout):
        super().__init__()
        self.dropout = dropout
        self.bn_ac_func1 = BNACConvLayer(num_channels, bn_size * growth_rate,
                                         1)
        self.bn_ac_func2 = BNACConvLayer(bn_size * growth_rate, growth_rate,
                                         3, pad=1)
        if dropout:
            self.dropout_func = nn.Dropout(p=dropout)

    def forward(self, x):
        conv = self.bn_ac_func1(x)
        conv = self.bn_ac_func2(conv)
        if self.dropout:
            conv = self.dropout_func(conv)
        return paddle_tpu.concat([x, conv], axis=1)


class DenseBlock(nn.Layer):
    def __init__(self, num_channels, num_layers, bn_size, growth_rate,
                 dropout):
        super().__init__()
        self.dense_layer_func = nn.LayerList()
        pre_channel = num_channels
        for _ in range(num_layers):
            self.dense_layer_func.append(
                DenseLayer(pre_channel, growth_rate, bn_size, dropout))
            pre_channel += growth_rate

    def forward(self, x):
        for func in self.dense_layer_func:
            x = func(x)
        return x


class TransitionLayer(nn.Layer):
    def __init__(self, num_channels, num_output_features):
        super().__init__()
        self.conv_ac_func = BNACConvLayer(num_channels, num_output_features,
                                          1)
        self.pool2d_avg = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool2d_avg(self.conv_ac_func(x))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        supported = {
            121: (64, 32, [6, 12, 24, 16]),
            161: (96, 48, [6, 12, 36, 24]),
            169: (64, 32, [6, 12, 32, 32]),
            201: (64, 32, [6, 12, 48, 32]),
            264: (64, 32, [6, 12, 64, 48]),
        }
        assert layers in supported, f"supported layers {list(supported)}"
        num_init_features, growth_rate, block_config = supported[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1_func = nn.Sequential(
            nn.Conv2D(3, num_init_features, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(num_init_features),
            nn.ReLU())
        self.pool2d_max = nn.MaxPool2D(3, stride=2, padding=1)

        self.block_config = block_config
        self.dense_block_func_list = nn.LayerList()
        self.transition_func_list = nn.LayerList()
        pre_num_channels = num_init_features
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            self.dense_block_func_list.append(DenseBlock(
                pre_num_channels, num_layers, bn_size, growth_rate, dropout))
            num_features = pre_num_channels + num_layers * growth_rate
            pre_num_channels = num_features
            if i != len(block_config) - 1:
                self.transition_func_list.append(
                    TransitionLayer(num_features, num_features // 2))
                pre_num_channels = num_features // 2

        self.batch_norm = nn.BatchNorm2D(num_features)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.out = nn.Linear(num_features, num_classes)

    def forward(self, x):
        x = self.conv1_func(x)
        x = self.pool2d_max(x)
        for i, block in enumerate(self.dense_block_func_list):
            x = block(x)
            if i != len(self.block_config) - 1:
                x = self.transition_func_list[i](x)
        x = self.relu(self.batch_norm(x))
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            from paddle_tpu.tensor.manipulation import flatten
            x = flatten(x, 1)
            x = self.out(x)
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(layers=121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return DenseNet(layers=161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(layers=169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(layers=201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(layers=264, **kwargs)
