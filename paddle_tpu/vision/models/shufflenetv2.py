"""ShuffleNetV2. Reference: python/paddle/vision/models/shufflenetv2.py.

Channel shuffle uses nn.ChannelShuffle (reshape+transpose — free under XLA
layout assignment).
"""
from __future__ import annotations

import paddle_tpu
import paddle_tpu.nn as nn


def create_activation_layer(act):
    if act == "swish":
        return nn.Swish
    if act == "relu":
        return nn.ReLU
    if act is None:
        return nn.Identity   # "no activation" must still be constructible
    raise ValueError(f"unsupported activation {act}")


class InvertedResidual(nn.Layer):
    def __init__(self, in_channels, out_channels, stride, act_layer=nn.ReLU):
        super().__init__()
        self._conv_pw = nn.Sequential(
            nn.Conv2D(in_channels // 2, out_channels // 2, 1, bias_attr=False),
            nn.BatchNorm2D(out_channels // 2), act_layer())
        self._conv_dw = nn.Sequential(
            nn.Conv2D(out_channels // 2, out_channels // 2, 3, stride=stride,
                      padding=1, groups=out_channels // 2, bias_attr=False),
            nn.BatchNorm2D(out_channels // 2))
        self._conv_linear = nn.Sequential(
            nn.Conv2D(out_channels // 2, out_channels // 2, 1,
                      bias_attr=False),
            nn.BatchNorm2D(out_channels // 2), act_layer())
        self._shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        x1, x2 = paddle_tpu.split(x, 2, axis=1)
        x2 = self._conv_pw(x2)
        x2 = self._conv_dw(x2)
        x2 = self._conv_linear(x2)
        out = paddle_tpu.concat([x1, x2], axis=1)
        return self._shuffle(out)


class InvertedResidualDS(nn.Layer):
    """Downsampling variant: both branches convolve, stride 2."""

    def __init__(self, in_channels, out_channels, stride, act_layer=nn.ReLU):
        super().__init__()
        self._conv_dw_1 = nn.Sequential(
            nn.Conv2D(in_channels, in_channels, 3, stride=stride, padding=1,
                      groups=in_channels, bias_attr=False),
            nn.BatchNorm2D(in_channels))
        self._conv_linear_1 = nn.Sequential(
            nn.Conv2D(in_channels, out_channels // 2, 1, bias_attr=False),
            nn.BatchNorm2D(out_channels // 2), act_layer())
        self._conv_pw_2 = nn.Sequential(
            nn.Conv2D(in_channels, out_channels // 2, 1, bias_attr=False),
            nn.BatchNorm2D(out_channels // 2), act_layer())
        self._conv_dw_2 = nn.Sequential(
            nn.Conv2D(out_channels // 2, out_channels // 2, 3, stride=stride,
                      padding=1, groups=out_channels // 2, bias_attr=False),
            nn.BatchNorm2D(out_channels // 2))
        self._conv_linear_2 = nn.Sequential(
            nn.Conv2D(out_channels // 2, out_channels // 2, 1,
                      bias_attr=False),
            nn.BatchNorm2D(out_channels // 2), act_layer())
        self._shuffle = nn.ChannelShuffle(2)

    def forward(self, x):
        x1 = self._conv_linear_1(self._conv_dw_1(x))
        x2 = self._conv_linear_2(self._conv_dw_2(self._conv_pw_2(x)))
        out = paddle_tpu.concat([x1, x2], axis=1)
        return self._shuffle(out)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        act_layer = create_activation_layer(act)

        if scale == 0.25:
            stage_out_channels = [-1, 24, 24, 48, 96, 512]
        elif scale == 0.33:
            stage_out_channels = [-1, 24, 32, 64, 128, 512]
        elif scale == 0.5:
            stage_out_channels = [-1, 24, 48, 96, 192, 1024]
        elif scale == 1.0:
            stage_out_channels = [-1, 24, 116, 232, 464, 1024]
        elif scale == 1.5:
            stage_out_channels = [-1, 24, 176, 352, 704, 1024]
        elif scale == 2.0:
            stage_out_channels = [-1, 24, 244, 488, 976, 2048]
        else:
            raise NotImplementedError(f"scale {scale} not supported")

        self._conv1 = nn.Sequential(
            nn.Conv2D(3, stage_out_channels[1], 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(stage_out_channels[1]), act_layer())
        self._max_pool = nn.MaxPool2D(3, stride=2, padding=1)

        blocks = []
        for stage_id, num_repeat in enumerate(stage_repeats):
            for i in range(num_repeat):
                if i == 0:
                    blocks.append(InvertedResidualDS(
                        stage_out_channels[stage_id + 1],
                        stage_out_channels[stage_id + 2], 2, act_layer))
                else:
                    blocks.append(InvertedResidual(
                        stage_out_channels[stage_id + 2],
                        stage_out_channels[stage_id + 2], 1, act_layer))
        self._blocks = nn.Sequential(*blocks)
        self._last_conv = nn.Sequential(
            nn.Conv2D(stage_out_channels[-2], stage_out_channels[-1], 1,
                      bias_attr=False),
            nn.BatchNorm2D(stage_out_channels[-1]), act_layer())
        if with_pool:
            self._pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self._fc = nn.Linear(stage_out_channels[-1], num_classes)

    def forward(self, x):
        x = self._conv1(x)
        x = self._max_pool(x)
        x = self._blocks(x)
        x = self._last_conv(x)
        if self.with_pool:
            x = self._pool2d_avg(x)
        if self.num_classes > 0:
            from paddle_tpu.tensor.manipulation import flatten
            x = flatten(x, 1)
            x = self._fc(x)
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
