"""SqueezeNet 1.0/1.1. Reference: python/paddle/vision/models/squeezenet.py."""
from __future__ import annotations

import paddle_tpu
import paddle_tpu.nn as nn


class MakeFire(nn.Layer):
    def __init__(self, in_channels, squeeze_channels, expand1x1_channels,
                 expand3x3_channels):
        super().__init__()
        self.squeeze = nn.Conv2D(in_channels, squeeze_channels, 1)
        self.expand1x1 = nn.Conv2D(squeeze_channels, expand1x1_channels, 1)
        self.expand3x3 = nn.Conv2D(squeeze_channels, expand3x3_channels, 3,
                                   padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return paddle_tpu.concat(
            [self.relu(self.expand1x1(x)), self.relu(self.expand3x3(x))],
            axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool

        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2),
                nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                MakeFire(96, 16, 64, 64),
                MakeFire(128, 16, 64, 64),
                MakeFire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                MakeFire(256, 32, 128, 128),
                MakeFire(256, 48, 192, 192),
                MakeFire(384, 48, 192, 192),
                MakeFire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                MakeFire(512, 64, 256, 256),
            )
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2, padding=1),
                nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                MakeFire(64, 16, 64, 64),
                MakeFire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                MakeFire(128, 32, 128, 128),
                MakeFire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                MakeFire(256, 48, 192, 192),
                MakeFire(384, 48, 192, 192),
                MakeFire(384, 64, 256, 256),
                MakeFire(512, 64, 256, 256),
            )
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5),
                nn.Conv2D(512, num_classes, 1),
                nn.ReLU())
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.avgpool(x)
        from paddle_tpu.tensor.manipulation import flatten
        return flatten(x, 1)


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet(version="1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet(version="1.1", **kwargs)
