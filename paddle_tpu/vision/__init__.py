from paddle_tpu.vision import datasets, models, ops, transforms  # noqa: F401
from paddle_tpu.vision.ops import decode_jpeg, read_file  # noqa: F401
# the reference surfaces the detection ops at paddle.vision level too
from paddle_tpu.vision.ops import (  # noqa: F401
    DeformConv2D,
    PSRoIPool,
    RoIAlign,
    RoIPool,
    box_coder,
    deform_conv2d,
    distribute_fpn_proposals,
    generate_proposals,
    matrix_nms,
    nms,
    prior_box,
    psroi_pool,
    roi_align,
    roi_pool,
    yolo_box,
    yolo_loss,
)


def set_image_backend(backend):
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")
    _state["image_backend"] = backend


def get_image_backend():
    return _state["image_backend"]


_state = {"image_backend": "pil"}


def image_load(path, backend=None):
    """Load an image file. PIL/cv2 are not in this build; PNG/PPM decode
    through pure numpy would go here — currently raises with guidance."""
    raise RuntimeError(
        "no image decoding library (PIL/cv2) is bundled in this build; "
        "decode to a numpy array yourself and feed it to the transforms "
        "(they accept HWC ndarrays)")

# reference layout parity: paddle.vision.transforms.functional is a
# submodule; here the functional forms live in the same module.  The
# attribute alias serves `from ...transforms import functional`; the
# sys.modules entry serves `import ...transforms.functional as F`.
import sys as _sys

transforms.functional = transforms
_sys.modules[__name__ + ".transforms.functional"] = transforms
