from paddle_tpu.vision import datasets, models, ops, transforms  # noqa: F401

# reference layout parity: paddle.vision.transforms.functional is a
# submodule; here the functional forms live in the same module.  The
# attribute alias serves `from ...transforms import functional`; the
# sys.modules entry serves `import ...transforms.functional as F`.
import sys as _sys

transforms.functional = transforms
_sys.modules[__name__ + ".transforms.functional"] = transforms
