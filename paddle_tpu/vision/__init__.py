from paddle_tpu.vision import datasets, models, transforms  # noqa: F401
