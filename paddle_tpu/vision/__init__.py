from paddle_tpu.vision import datasets, models, ops, transforms  # noqa: F401
