"""Training callbacks. Reference: python/paddle/hapi/callbacks.py."""
from __future__ import annotations

import numpy as np


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            print(f"step {step}: {logs}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0
        self.mode = "min" if mode in ("auto", "min") else "max"
        self.stop_training = False

    def on_eval_end(self, logs=None):
        cur = logs.get(self.monitor) if logs else None
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        better = (self.best is None or
                  (cur < self.best - self.min_delta if self.mode == "min"
                   else cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True


class VisualDL(Callback):
    def __init__(self, log_dir="./log"):
        self.log_dir = log_dir
        self.records = []

    def on_train_batch_end(self, step, logs=None):
        self.records.append((step, logs))


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer lr by `factor` after `patience` evals without
    improvement on `monitor` (reference hapi/callbacks.py:1169)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = patience
        self.verbose = verbose
        if mode == "auto":
            mode = "max" if "acc" in (monitor or "") else "min"
        self.mode = mode
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._better(cur):
            self.best = cur
            self.wait = 0
            return
        if self.cooldown_counter > 0:
            return
        self.wait += 1
        if self.wait < self.patience:
            return
        opt = getattr(self.model, "_optimizer", None)
        if opt is None:
            return
        lr = opt.get_lr()
        new_lr = max(lr * self.factor, self.min_lr)
        if new_lr < lr:
            opt.set_lr(new_lr)
            if self.verbose:
                print(f"ReduceLROnPlateau: lr {lr:.3e} -> {new_lr:.3e}")
        self.cooldown_counter = self.cooldown
        self.wait = 0


class WandbCallback(Callback):
    """Weights & Biases logger (reference hapi/callbacks.py WandbCallback).
    Requires the `wandb` package, which this zero-egress build does not
    bundle — construction degrades to a local record unless wandb is
    importable."""

    def __init__(self, project=None, name=None, dir=None, mode=None,
                 job_type=None, **kwargs):
        self.wandb = None
        self.run = None
        self.records = []
        try:
            import wandb
        except ImportError:
            # the expected case in this zero-egress build: degrade
            # silently to the local record
            return
        try:
            self.wandb = wandb
            self.run = wandb.init(project=project, name=name, dir=dir,
                                  mode=mode, job_type=job_type, **kwargs)
        except Exception as e:  # noqa: BLE001 — auth/network/config
            # errors degrade too (training must not crash at callback
            # construction), but UNLIKE a missing package this is a real
            # failure the user believes is working — say so
            import warnings
            warnings.warn(
                f"WandbCallback: wandb.init failed "
                f"({type(e).__name__}: {e}); degrading to local records "
                f"— runs are NOT being logged to W&B", RuntimeWarning,
                stacklevel=2)
            self.wandb = None
            self.run = None

    def on_train_batch_end(self, step, logs=None):
        if self.run is not None:
            self.run.log(dict(logs or {}), step=step)
        else:
            self.records.append(("train", step, dict(logs or {})))

    def on_eval_end(self, logs=None):
        if self.run is not None:
            self.run.log({f"eval/{k}": v for k, v in (logs or {}).items()})
        else:
            self.records.append(("eval", None, dict(logs or {})))

    def on_train_end(self, logs=None):
        if self.run is not None:
            self.run.finish()
